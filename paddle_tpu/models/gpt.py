"""GPT: decoder-only causal language model — the flagship training workload
(BASELINE config 5: "Fleet hybrid-parallel GPT-3 1.3B pp+dp").

Built from the framework's own transformer layers (the reference builds GPT
the same way on python/paddle/nn/layer/transformer.py MultiHeadAttention /
TransformerEncoder; the 1.3B fleet example lives in the PaddleNLP repo, its
parallel form in fleet/meta_parallel/parallel_layers/mp_layers.py).

TPU notes:
- pre-norm (normalize_before=True) transformer blocks, bf16-friendly.
- the causal mask is a static additive mask folded into attention — XLA fuses
  it; no dynamic masking code path.
- `tp_partition_specs()` returns the tensor-parallel PartitionSpec plan for
  every parameter (Megatron-style column/row split over the "mp" mesh axis:
  reference mp_layers.py:96 ColumnParallelLinear / :169 RowParallelLinear /
  :29 VocabParallelEmbedding) — consumed by fleet's planner and the
  multi-chip dryrun.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from ..core.tensor import Tensor
from .. import ops
from ..nn.layer_base import Layer
from ..nn import (Embedding, LayerNorm, Linear, Dropout, TransformerEncoder,
                  TransformerEncoderLayer)
from ..nn import functional as F


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    # "auto": Pallas flash attention above the measured S>=4096 crossover
    # (nn/transformer.py FLASH_CROSSOVER), dense below; "flash"/"dense"
    # force either. Training with attention_dropout_prob > 0 stays dense
    # (the fused kernel never materialises the prob matrix to drop).
    attn_impl: str = "auto"
    # explicit (block_q, block_k) for the flash kernel; None = ask the
    # paddle_tpu.tuner winner cache for this (shape, dtype, platform)
    attn_blocks: Optional[tuple] = None

    @property
    def ffn_size(self):
        return self.intermediate_size or 4 * self.hidden_size

    def draft(self, scale: int = 4, *, hidden_size: Optional[int] = None,
              num_layers: Optional[int] = None,
              num_heads: Optional[int] = None) -> "GPTConfig":
        """A small draft-model config for speculative decoding against
        this target: SAME vocab and positions (the verify step compares
        token ids and shares the position range), everything else shrunk
        by ``scale`` unless given explicitly. Heads are reduced until they
        divide the draft hidden size."""
        h = hidden_size if hidden_size is not None \
            else max(1, self.hidden_size // scale)
        nl = num_layers if num_layers is not None \
            else max(1, self.num_layers // scale)
        nh = num_heads if num_heads is not None \
            else max(1, self.num_heads // scale)
        while h % nh:
            nh -= 1
        return GPTConfig(
            vocab_size=self.vocab_size, hidden_size=h, num_layers=nl,
            num_heads=nh,
            max_position_embeddings=self.max_position_embeddings,
            hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
            attn_impl=self.attn_impl)


class GPTModel(Layer):
    """Token + position embedding → pre-norm decoder stack → final norm."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        c = config
        from ..nn.layer_base import ParamAttr
        from ..nn import initializer as I
        emb_attr = ParamAttr(initializer=I.Normal(0.0, 0.02))
        self.word_embeddings = Embedding(c.vocab_size, c.hidden_size,
                                         weight_attr=emb_attr)
        self.position_embeddings = Embedding(c.max_position_embeddings,
                                             c.hidden_size,
                                             weight_attr=ParamAttr(
                                                 initializer=I.Normal(0.0, 0.02)))
        self.embedding_dropout = Dropout(c.hidden_dropout_prob)
        layer = TransformerEncoderLayer(
            c.hidden_size, c.num_heads, c.ffn_size,
            dropout=c.hidden_dropout_prob, activation="gelu",
            attn_dropout=c.attention_dropout_prob, normalize_before=True,
            attn_impl=getattr(c, "attn_impl", "auto"),
            attn_blocks=getattr(c, "attn_blocks", None))
        self.decoder = TransformerEncoder(layer, c.num_layers,
                                          norm=LayerNorm(c.hidden_size))

    def gen_cache(self, input_ids):
        """Per-layer incremental KV caches for autoregressive decoding
        (reference: TransformerEncoder.gen_cache). The layer gen_cache
        reads only batch size and dtype, so seed it from a single-token
        embedding slice instead of embedding the whole prompt."""
        h0 = self.word_embeddings(input_ids[:, :1])
        return self.decoder.gen_cache(h0)

    def forward(self, input_ids, position_ids=None, cache=None):
        seq_len = input_ids.shape[1]
        if position_ids is None:
            # with a KV cache the new tokens sit AFTER the cached prefix
            offset = int(cache[0].k.shape[2]) if cache is not None else 0
            position_ids = ops.arange(offset, offset + seq_len,
                                      dtype="int32")
            position_ids = ops.expand(ops.unsqueeze(position_ids, 0),
                                      [input_ids.shape[0], seq_len])
        h = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids))
        h = self.embedding_dropout(h)
        # causal mask as the CAUSAL_MASK sentinel: the flash path applies
        # causality inside the kernel, the dense path materialises the
        # additive triu lazily with the cached-prefix offset
        # (nn/transformer.py MultiHeadAttention)
        from ..nn.transformer import CAUSAL_MASK
        if cache is None:
            return self.decoder(h, src_mask=CAUSAL_MASK)
        return self.decoder(h, src_mask=CAUSAL_MASK, cache=cache)


class GPTForCausalLM(Layer):
    """LM head tied to the word embedding (reference GPT convention)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)

    def forward(self, input_ids, position_ids=None, cache=None):
        out = self.gpt(input_ids, position_ids, cache=cache)
        h, new_cache = out if cache is not None else (out, None)
        # logits = h @ E^T with the tied embedding matrix
        logits = ops.matmul(h, self.gpt.word_embeddings.weight,
                            transpose_y=True)
        return logits if cache is None else (logits, new_cache)


class GPTPretrainingCriterion(Layer):
    """Shifted next-token cross entropy."""

    def forward(self, logits, labels):
        v = logits.shape[-1]
        flat = ops.reshape(logits[:, :-1, :], [-1, v])
        tgt = ops.reshape(labels[:, 1:], [-1])
        return F.cross_entropy(flat, tgt)


# -- tensor-parallel plan -----------------------------------------------------

_TP_RULES = (
    # Megatron split: qkv + ffn-in are column-parallel (shard output dim),
    # attn-out + ffn-out are row-parallel (shard input dim), embeddings are
    # vocab/position-sharded on the table dim.
    (r"\.(q_proj|k_proj|v_proj|linear1)\.weight$", (None, "mp")),
    (r"\.(q_proj|k_proj|v_proj|linear1)\.bias$", ("mp",)),
    (r"\.(out_proj|linear2)\.weight$", ("mp", None)),
    (r"word_embeddings\.weight$", ("mp", None)),
)


def tp_partition_specs(model: Layer) -> Dict[str, tuple]:
    """Per-parameter PartitionSpec axes (as tuples; () = replicated) for
    tensor parallelism over the "mp" mesh axis."""
    specs = {}
    for name, p in model.named_parameters():
        spec = ()
        for pat, s in _TP_RULES:
            if re.search(pat, name):
                spec = s
                break
        specs[name] = spec
    return specs


# -- pipeline plan ------------------------------------------------------------

def gpt_pipeline_fns(model: "GPTForCausalLM", num_stages: int):
    """Decompose a GPTForCausalLM into (embed_fn, block_fn, head_fn) pure
    functions + their param trees for the compiled heterogeneous pipeline
    engine (fleet.pipeline_engine.gpipe_blocks): embedding runs as stage
    0's preamble, each stage applies num_layers/num_stages decoder blocks
    (params stacked [S, k, ...] and sharded over "pp"), and the head (final
    norm + tied-embedding logits + shifted CE loss) runs on the last stage.

    The reference schedules these heterogeneous stage signatures with a
    runtime handshake (fleet/meta_parallel/pipeline_parallel.py:272
    _send_meta); here they are fixed at build time. Dropout must be 0 (the
    engine threads no RNG through the schedule).
    """
    import jax
    import jax.numpy as jnp
    from ..jit.functionalize import build_pure

    cfg = model.gpt.config
    if cfg.hidden_dropout_prob or cfg.attention_dropout_prob:
        raise ValueError("gpt_pipeline_fns requires dropout 0")
    L, S = cfg.num_layers, int(num_stages)
    if L % S != 0:
        raise ValueError(f"{L} layers not divisible by {S} stages")
    k = L // S

    emb = model.gpt.word_embeddings.weight._data
    pos = model.gpt.position_embeddings.weight._data
    dec_layers = list(model.gpt.decoder.layers)
    final_norm = model.gpt.decoder.norm

    # one pure fn traced from a representative block; per-stage params are
    # the per-layer raw lists, stacked [S, k, ...]
    layer0_params = [p for _, p in dec_layers[0].named_parameters()]
    block_pure, _ = build_pure(dec_layers[0].forward, layer0_params)
    per_layer_raws = [[p._data for _, p in lyr.named_parameters()]
                     for lyr in dec_layers]
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[jax.tree_util.tree_map(
            lambda *ys: jnp.stack(ys), *per_layer_raws[s * k:(s + 1) * k])
          for s in range(S)])

    norm_params = [p for _, p in final_norm.named_parameters()]
    norm_pure, _ = build_pure(final_norm.forward, norm_params)
    norm_raws = [p._data for p in norm_params]

    key = jax.random.PRNGKey(0)  # unused: dropout is 0

    def _mask(h):
        L_seq = h.shape[1]
        m = jnp.triu(jnp.full((L_seq, L_seq), -1e4, h.dtype), 1)
        return m[None, None]

    def embed_fn(p, ids):
        seq = ids.shape[1]
        return p["tok"][ids] + p["pos"][None, :seq, :]

    def block_fn(stage_params, h):
        for i in range(k):
            lp = jax.tree_util.tree_map(lambda a: a[i], stage_params)
            h = block_pure(lp, (h, _mask(h)), key, None)[0]
        return h

    def head_fn(p, h, xy):
        ids = xy if not isinstance(xy, tuple) else xy[0]
        h = norm_pure(p["norm"], (h,), key, None)[0]
        logits = h @ p["tok"].T
        lo = jax.nn.log_softmax(logits[:, :-1, :].astype(jnp.float32))
        tgt = ids[:, 1:]
        nll = -jnp.take_along_axis(lo, tgt[..., None].astype(jnp.int32),
                                   axis=-1)
        return jnp.mean(nll)

    embed_params = {"tok": emb, "pos": pos}
    head_params = {"tok": emb, "norm": norm_raws}
    block_tensors = [[p for _, p in lyr.named_parameters()]
                     for lyr in dec_layers]
    return {
        "embed_fn": embed_fn, "block_fn": block_fn, "head_fn": head_fn,
        "embed_params": embed_params, "stacked_block_params": stacked,
        "head_params": head_params,
        "param_tensors": {
            "embed": [model.gpt.word_embeddings.weight,
                      model.gpt.position_embeddings.weight],
            "blocks": block_tensors, "norm": norm_params,
        },
        "stages": S, "layers_per_stage": k,
    }


#: how many tokens each decode runs between host-side "all rows hit eos?"
#: probes — the probe is a device->host sync, so amortizing it keeps decode
#: device-bound; frozen rows keep emitting eos, so the only cost of a late
#: stop is trimmed-off work, never wrong tokens.
_EOS_CHECK_EVERY = 8


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _trim_generated(gen: "np.ndarray", eos_token_id) -> int:
    """Columns of the generated block to keep: the step at which every row
    had emitted eos, plus one — exactly where the per-token-checking loop
    used to break. Rows that never emit eos keep the full budget."""
    import numpy as np
    if eos_token_id is None or gen.shape[1] == 0:
        return gen.shape[1]
    hits = gen == eos_token_id
    if not hits.any(axis=1).all():
        return gen.shape[1]
    return int(hits.argmax(axis=1).max()) + 1


def _gpt_generate_static(model, ids, max_length, decode_strategy, top_k,
                         temperature, eos_token_id):
    """Static-slot decode: prefill once, then ONE compiled decode step per
    token over fixed [B, max_seq] shapes (paddle_tpu.serving.llm.decode) —
    no per-token retrace, no per-token host sync. Token-for-token
    equivalent to the concat-cache path (same math, same sampling recipe,
    same per-step generator keys)."""
    import numpy as np
    from ..core import generator as _gen
    from ..core.tensor import Tensor
    import jax
    import jax.numpy as jnp
    from ..serving.llm.decode import (GPTStaticDecoder, SamplingParams,
                                      pack_sampling)

    b, lin = int(ids.shape[0]), int(ids.shape[1])
    max_pos = model.gpt.config.max_position_embeddings
    # pow2-rounded shapes so repeat calls with nearby lengths reuse the
    # compiled step (the shape pair keys the executable)
    max_seq = min(_next_pow2(lin + int(max_length)), max_pos)
    lp = min(_next_pow2(lin), max_seq)
    do_sample = decode_strategy == "sampling" and top_k != 1
    dec = GPTStaticDecoder(
        model, max_top_k=int(top_k) if do_sample and top_k else 0)
    kv = dec.new_kv(b, max_seq)
    params = dec.params()
    samp = SamplingParams(
        do_sample=do_sample, temperature=float(temperature),
        top_k=int(top_k) if do_sample else 0, eos_token_id=eos_token_id,
        max_new_tokens=int(max_length))
    svecs = pack_sampling([samp] * b)
    fixed_key = jax.random.PRNGKey(0)   # greedy consumes no generator keys

    padded = np.zeros((b, lp), np.int32)
    padded[:, :lin] = np.asarray(jax.device_get(ids))  # noqa: PTA002 -- one prompt download to build the padded prefill batch (admission-time, not per-token)
    finished = jnp.zeros((b,), jnp.bool_)
    key = _gen.next_key() if do_sample else fixed_key
    nxt, finished = dec.prefill(
        kv, params, jnp.asarray(padded),
        jnp.full((b,), lin, jnp.int32), jnp.arange(b, dtype=jnp.int32),
        finished, svecs, key)
    gen = jnp.zeros((b, int(max_length)), jnp.int32).at[:, 0].set(nxt)
    last = nxt
    steps = 1
    for t in range(1, int(max_length)):
        key = _gen.next_key() if do_sample else fixed_key
        nxt, finished = dec.decode_step(kv, params, finished, last, svecs,
                                        key)
        last = nxt
        gen = gen.at[:, t].set(nxt)
        steps = t + 1
        if eos_token_id is not None and t % _EOS_CHECK_EVERY == 0:
            # the amortized finish probe: one [B]-bool reduce every
            # _EOS_CHECK_EVERY tokens instead of a sync per token
            if bool(np.asarray(jax.device_get(jnp.all(finished)))):  # noqa: PTA002 -- deliberate amortized early-exit probe; frozen rows emit eos so late detection only trims work
                break
    gen_h = np.asarray(jax.device_get(gen[:, :steps]))  # noqa: PTA002 -- single end-of-generate download of the token matrix (the return value)
    keep = _trim_generated(gen_h, eos_token_id)
    out = np.concatenate(
        [np.asarray(jax.device_get(ids)), gen_h[:, :keep]], axis=1)  # noqa: PTA002 -- stitching the host return value
    return Tensor(jnp.asarray(out, jnp.int32))


def _gpt_generate(model, input_ids, max_length=32, decode_strategy="greedy",
                  top_k=1, temperature=1.0, eos_token_id=None,
                  use_cache=True):
    """Autoregressive decoding for GPTForCausalLM (reference capability:
    PaddleNLP GenerationMixin.generate — greedy / top-k sampling; the
    beam form lives in nn.BeamSearchDecoder/dynamic_decode).

    ``use_cache=True`` (default) decodes through the static-slot KV cache:
    prefill writes the prompt K/V into preallocated ``[B, max_seq]``
    buffers and every token then reuses ONE compiled decode step — no
    shape growth, no per-token retrace. ``use_cache="concat"`` keeps the
    legacy concat-grown MHA cache (incremental but retraces per length);
    ``use_cache=False`` recomputes the full prefix each step (O(T^2), the
    testing reference). All three are token-identical. Returns ids
    [B, input_len + n_generated] (n_generated < max_length only when
    every row emitted ``eos_token_id``)."""
    import numpy as np
    from ..core import generator as _gen
    from ..core.tensor import Tensor
    import jax
    import jax.numpy as jnp

    if decode_strategy not in ("greedy", "sampling"):
        raise ValueError(
            f"decode_strategy {decode_strategy!r} not in "
            f"('greedy', 'sampling'); beam search = "
            f"nn.BeamSearchDecoder + dynamic_decode")
    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids), jnp.int32)
    c = model.gpt.config
    if use_cache is True:
        # static-slot fast path needs the deterministic eval math (the
        # compiled step has no dropout) and room in the position table;
        # otherwise fall through to the concat cache below
        dropout_off = (not getattr(model, "training", False)) or (
            c.hidden_dropout_prob == 0.0 and c.attention_dropout_prob == 0.0)
        if dropout_off and ids.shape[1] + int(max_length) <= \
                c.max_position_embeddings and int(max_length) >= 1:
            return _gpt_generate_static(
                model, ids, max_length, decode_strategy, top_k,
                temperature, eos_token_id)
        use_cache = "concat"
    finished = jnp.zeros((ids.shape[0],), jnp.bool_)
    cache = None
    if use_cache:
        cache = model.gpt.gen_cache(Tensor(ids))
    step_input = ids
    n_steps = int(max_length)
    for step in range(n_steps):
        if use_cache:
            logits, cache = model(Tensor(step_input), cache=cache)
        else:
            logits = model(Tensor(ids))
        lraw = logits._data[:, -1, :].astype(jnp.float32)
        if decode_strategy == "greedy" or top_k == 1:
            nxt = jnp.argmax(lraw, axis=-1).astype(jnp.int32)
        else:   # sampling
            lraw = lraw / max(float(temperature), 1e-6)
            if top_k and top_k > 0:
                kth = jax.lax.top_k(lraw, int(top_k))[0][:, -1:]
                lraw = jnp.where(lraw < kth, -1e9, lraw)
            nxt = jax.random.categorical(_gen.next_key(), lraw,
                                         axis=-1).astype(jnp.int32)
        if eos_token_id is not None:
            # rows that already emitted eos are frozen to eos (reference
            # GenerationMixin per-row finished semantics)
            nxt = jnp.where(finished, jnp.asarray(eos_token_id,
                                                  nxt.dtype), nxt)
            finished = finished | (nxt == eos_token_id)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
        step_input = nxt[:, None]          # cache path: one new token
        if eos_token_id is not None and step % _EOS_CHECK_EVERY == \
                _EOS_CHECK_EVERY - 1:
            # amortized early-exit probe (was a per-token host sync);
            # overshoot columns are frozen eos and trimmed below
            if bool(jnp.all(finished)):  # noqa: PTA002 -- deliberate amortized device->host probe, every _EOS_CHECK_EVERY tokens
                break
    if eos_token_id is not None and n_steps > 0:
        lin = int(ids.shape[1]) - (step + 1)   # step = last loop index run
        full = np.asarray(jax.device_get(ids))  # noqa: PTA002 -- end-of-generate download to trim frozen-eos overshoot (the return value is host-bound anyway)
        keep = _trim_generated(full[:, lin:], eos_token_id)
        return Tensor(jnp.asarray(full[:, :lin + keep], jnp.int32))
    return Tensor(ids)


GPTForCausalLM.generate = _gpt_generate
