"""GPT: decoder-only causal language model — the flagship training workload
(BASELINE config 5: "Fleet hybrid-parallel GPT-3 1.3B pp+dp").

Built from the framework's own transformer layers (the reference builds GPT
the same way on python/paddle/nn/layer/transformer.py MultiHeadAttention /
TransformerEncoder; the 1.3B fleet example lives in the PaddleNLP repo, its
parallel form in fleet/meta_parallel/parallel_layers/mp_layers.py).

TPU notes:
- pre-norm (normalize_before=True) transformer blocks, bf16-friendly.
- the causal mask is a static additive mask folded into attention — XLA fuses
  it; no dynamic masking code path.
- `tp_partition_specs()` returns the tensor-parallel PartitionSpec plan for
  every parameter (Megatron-style column/row split over the "mp" mesh axis:
  reference mp_layers.py:96 ColumnParallelLinear / :169 RowParallelLinear /
  :29 VocabParallelEmbedding) — consumed by fleet's planner and the
  multi-chip dryrun.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from ..core.tensor import Tensor
from .. import ops
from ..nn.layer_base import Layer
from ..nn import (Embedding, LayerNorm, Linear, Dropout, TransformerEncoder,
                  TransformerEncoderLayer)
from ..nn import functional as F


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    # "auto": Pallas flash attention above the measured S>=4096 crossover
    # (nn/transformer.py FLASH_CROSSOVER), dense below; "flash"/"dense"
    # force either. Training with attention_dropout_prob > 0 stays dense
    # (the fused kernel never materialises the prob matrix to drop).
    attn_impl: str = "auto"

    @property
    def ffn_size(self):
        return self.intermediate_size or 4 * self.hidden_size


class GPTModel(Layer):
    """Token + position embedding → pre-norm decoder stack → final norm."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        c = config
        from ..nn.layer_base import ParamAttr
        from ..nn import initializer as I
        emb_attr = ParamAttr(initializer=I.Normal(0.0, 0.02))
        self.word_embeddings = Embedding(c.vocab_size, c.hidden_size,
                                         weight_attr=emb_attr)
        self.position_embeddings = Embedding(c.max_position_embeddings,
                                             c.hidden_size,
                                             weight_attr=ParamAttr(
                                                 initializer=I.Normal(0.0, 0.02)))
        self.embedding_dropout = Dropout(c.hidden_dropout_prob)
        layer = TransformerEncoderLayer(
            c.hidden_size, c.num_heads, c.ffn_size,
            dropout=c.hidden_dropout_prob, activation="gelu",
            attn_dropout=c.attention_dropout_prob, normalize_before=True,
            attn_impl=getattr(c, "attn_impl", "auto"))
        self.decoder = TransformerEncoder(layer, c.num_layers,
                                          norm=LayerNorm(c.hidden_size))

    def gen_cache(self, input_ids):
        """Per-layer incremental KV caches for autoregressive decoding
        (reference: TransformerEncoder.gen_cache). The layer gen_cache
        reads only batch size and dtype, so seed it from a single-token
        embedding slice instead of embedding the whole prompt."""
        h0 = self.word_embeddings(input_ids[:, :1])
        return self.decoder.gen_cache(h0)

    def forward(self, input_ids, position_ids=None, cache=None):
        seq_len = input_ids.shape[1]
        if position_ids is None:
            # with a KV cache the new tokens sit AFTER the cached prefix
            offset = int(cache[0].k.shape[2]) if cache is not None else 0
            position_ids = ops.arange(offset, offset + seq_len,
                                      dtype="int32")
            position_ids = ops.expand(ops.unsqueeze(position_ids, 0),
                                      [input_ids.shape[0], seq_len])
        h = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids))
        h = self.embedding_dropout(h)
        # causal mask as the CAUSAL_MASK sentinel: the flash path applies
        # causality inside the kernel, the dense path materialises the
        # additive triu lazily with the cached-prefix offset
        # (nn/transformer.py MultiHeadAttention)
        from ..nn.transformer import CAUSAL_MASK
        if cache is None:
            return self.decoder(h, src_mask=CAUSAL_MASK)
        return self.decoder(h, src_mask=CAUSAL_MASK, cache=cache)


class GPTForCausalLM(Layer):
    """LM head tied to the word embedding (reference GPT convention)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)

    def forward(self, input_ids, position_ids=None, cache=None):
        out = self.gpt(input_ids, position_ids, cache=cache)
        h, new_cache = out if cache is not None else (out, None)
        # logits = h @ E^T with the tied embedding matrix
        logits = ops.matmul(h, self.gpt.word_embeddings.weight,
                            transpose_y=True)
        return logits if cache is None else (logits, new_cache)


class GPTPretrainingCriterion(Layer):
    """Shifted next-token cross entropy."""

    def forward(self, logits, labels):
        v = logits.shape[-1]
        flat = ops.reshape(logits[:, :-1, :], [-1, v])
        tgt = ops.reshape(labels[:, 1:], [-1])
        return F.cross_entropy(flat, tgt)


# -- tensor-parallel plan -----------------------------------------------------

_TP_RULES = (
    # Megatron split: qkv + ffn-in are column-parallel (shard output dim),
    # attn-out + ffn-out are row-parallel (shard input dim), embeddings are
    # vocab/position-sharded on the table dim.
    (r"\.(q_proj|k_proj|v_proj|linear1)\.weight$", (None, "mp")),
    (r"\.(q_proj|k_proj|v_proj|linear1)\.bias$", ("mp",)),
    (r"\.(out_proj|linear2)\.weight$", ("mp", None)),
    (r"word_embeddings\.weight$", ("mp", None)),
)


def tp_partition_specs(model: Layer) -> Dict[str, tuple]:
    """Per-parameter PartitionSpec axes (as tuples; () = replicated) for
    tensor parallelism over the "mp" mesh axis."""
    specs = {}
    for name, p in model.named_parameters():
        spec = ()
        for pat, s in _TP_RULES:
            if re.search(pat, name):
                spec = s
                break
        specs[name] = spec
    return specs


# -- pipeline plan ------------------------------------------------------------

def gpt_pipeline_fns(model: "GPTForCausalLM", num_stages: int):
    """Decompose a GPTForCausalLM into (embed_fn, block_fn, head_fn) pure
    functions + their param trees for the compiled heterogeneous pipeline
    engine (fleet.pipeline_engine.gpipe_blocks): embedding runs as stage
    0's preamble, each stage applies num_layers/num_stages decoder blocks
    (params stacked [S, k, ...] and sharded over "pp"), and the head (final
    norm + tied-embedding logits + shifted CE loss) runs on the last stage.

    The reference schedules these heterogeneous stage signatures with a
    runtime handshake (fleet/meta_parallel/pipeline_parallel.py:272
    _send_meta); here they are fixed at build time. Dropout must be 0 (the
    engine threads no RNG through the schedule).
    """
    import jax
    import jax.numpy as jnp
    from ..jit.functionalize import build_pure

    cfg = model.gpt.config
    if cfg.hidden_dropout_prob or cfg.attention_dropout_prob:
        raise ValueError("gpt_pipeline_fns requires dropout 0")
    L, S = cfg.num_layers, int(num_stages)
    if L % S != 0:
        raise ValueError(f"{L} layers not divisible by {S} stages")
    k = L // S

    emb = model.gpt.word_embeddings.weight._data
    pos = model.gpt.position_embeddings.weight._data
    dec_layers = list(model.gpt.decoder.layers)
    final_norm = model.gpt.decoder.norm

    # one pure fn traced from a representative block; per-stage params are
    # the per-layer raw lists, stacked [S, k, ...]
    layer0_params = [p for _, p in dec_layers[0].named_parameters()]
    block_pure, _ = build_pure(dec_layers[0].forward, layer0_params)
    per_layer_raws = [[p._data for _, p in lyr.named_parameters()]
                     for lyr in dec_layers]
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[jax.tree_util.tree_map(
            lambda *ys: jnp.stack(ys), *per_layer_raws[s * k:(s + 1) * k])
          for s in range(S)])

    norm_params = [p for _, p in final_norm.named_parameters()]
    norm_pure, _ = build_pure(final_norm.forward, norm_params)
    norm_raws = [p._data for p in norm_params]

    key = jax.random.PRNGKey(0)  # unused: dropout is 0

    def _mask(h):
        L_seq = h.shape[1]
        m = jnp.triu(jnp.full((L_seq, L_seq), -1e4, h.dtype), 1)
        return m[None, None]

    def embed_fn(p, ids):
        seq = ids.shape[1]
        return p["tok"][ids] + p["pos"][None, :seq, :]

    def block_fn(stage_params, h):
        for i in range(k):
            lp = jax.tree_util.tree_map(lambda a: a[i], stage_params)
            h = block_pure(lp, (h, _mask(h)), key, None)[0]
        return h

    def head_fn(p, h, xy):
        ids = xy if not isinstance(xy, tuple) else xy[0]
        h = norm_pure(p["norm"], (h,), key, None)[0]
        logits = h @ p["tok"].T
        lo = jax.nn.log_softmax(logits[:, :-1, :].astype(jnp.float32))
        tgt = ids[:, 1:]
        nll = -jnp.take_along_axis(lo, tgt[..., None].astype(jnp.int32),
                                   axis=-1)
        return jnp.mean(nll)

    embed_params = {"tok": emb, "pos": pos}
    head_params = {"tok": emb, "norm": norm_raws}
    block_tensors = [[p for _, p in lyr.named_parameters()]
                     for lyr in dec_layers]
    return {
        "embed_fn": embed_fn, "block_fn": block_fn, "head_fn": head_fn,
        "embed_params": embed_params, "stacked_block_params": stacked,
        "head_params": head_params,
        "param_tensors": {
            "embed": [model.gpt.word_embeddings.weight,
                      model.gpt.position_embeddings.weight],
            "blocks": block_tensors, "norm": norm_params,
        },
        "stages": S, "layers_per_stage": k,
    }


def _gpt_generate(model, input_ids, max_length=32, decode_strategy="greedy",
                  top_k=1, temperature=1.0, eos_token_id=None,
                  use_cache=True):
    """Autoregressive decoding for GPTForCausalLM (reference capability:
    PaddleNLP GenerationMixin.generate — greedy / top-k sampling; the
    beam form lives in nn.BeamSearchDecoder/dynamic_decode).

    ``use_cache=True`` (default) runs incremental decoding over the
    per-layer KV caches (each step attends new token vs cached prefix —
    O(T) work per token); ``use_cache=False`` recomputes the full prefix
    each step (O(T^2), kept as the reference for testing). Returns ids
    [B, input_len + max_length]."""
    import numpy as np
    from ..core import generator as _gen
    from ..core.tensor import Tensor
    import jax
    import jax.numpy as jnp

    if decode_strategy not in ("greedy", "sampling"):
        raise ValueError(
            f"decode_strategy {decode_strategy!r} not in "
            f"('greedy', 'sampling'); beam search = "
            f"nn.BeamSearchDecoder + dynamic_decode")
    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids), jnp.int32)
    finished = jnp.zeros((ids.shape[0],), jnp.bool_)
    cache = None
    if use_cache:
        cache = model.gpt.gen_cache(Tensor(ids))
    step_input = ids
    for _ in range(int(max_length)):
        if use_cache:
            logits, cache = model(Tensor(step_input), cache=cache)
        else:
            logits = model(Tensor(ids))
        lraw = logits._data[:, -1, :].astype(jnp.float32)
        if decode_strategy == "greedy" or top_k == 1:
            nxt = jnp.argmax(lraw, axis=-1).astype(jnp.int32)
        else:   # sampling
            lraw = lraw / max(float(temperature), 1e-6)
            if top_k and top_k > 0:
                kth = jax.lax.top_k(lraw, int(top_k))[0][:, -1:]
                lraw = jnp.where(lraw < kth, -1e9, lraw)
            nxt = jax.random.categorical(_gen.next_key(), lraw,
                                         axis=-1).astype(jnp.int32)
        if eos_token_id is not None:
            # rows that already emitted eos are frozen to eos (reference
            # GenerationMixin per-row finished semantics)
            nxt = jnp.where(finished, jnp.asarray(eos_token_id,
                                                  nxt.dtype), nxt)
            finished = finished | (nxt == eos_token_id)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
        step_input = nxt[:, None]          # cache path: one new token
        if eos_token_id is not None and bool(jnp.all(finished)):
            break
    return Tensor(ids)


GPTForCausalLM.generate = _gpt_generate
