"""BERT encoder family (BASELINE config 3: ERNIE-base / BERT-base finetune).

Built on the framework transformer layers the same way the reference
ecosystem does (reference: python/paddle/nn/layer/transformer.py:431
TransformerEncoderLayer; ERNIE/BERT definitions live in PaddleNLP on top of
them). Post-norm blocks, learned token/position/type embeddings, pooler.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .. import ops
from ..nn.layer_base import Layer
from ..nn import (Embedding, LayerNorm, Linear, Dropout, Tanh,
                  TransformerEncoder, TransformerEncoderLayer)


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1


class BertEmbeddings(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(c.vocab_size, c.hidden_size)
        self.position_embeddings = Embedding(c.max_position_embeddings,
                                             c.hidden_size)
        self.token_type_embeddings = Embedding(c.type_vocab_size, c.hidden_size)
        self.layer_norm = LayerNorm(c.hidden_size)
        self.dropout = Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        seq_len = input_ids.shape[1]
        if position_ids is None:
            position_ids = ops.expand(
                ops.unsqueeze(ops.arange(0, seq_len, dtype="int32"), 0),
                [input_ids.shape[0], seq_len])
        if token_type_ids is None:
            token_type_ids = ops.zeros_like(input_ids)
        h = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(h))


class BertPooler(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.dense = Linear(c.hidden_size, c.hidden_size)
        self.activation = Tanh()

    def forward(self, h):
        return self.activation(self.dense(h[:, 0]))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        c = config
        self.embeddings = BertEmbeddings(c)
        layer = TransformerEncoderLayer(
            c.hidden_size, c.num_heads, c.intermediate_size,
            dropout=c.hidden_dropout_prob, activation="gelu",
            attn_dropout=c.attention_dropout_prob, normalize_before=False)
        self.encoder = TransformerEncoder(layer, c.num_layers)
        self.pooler = BertPooler(c)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        if attention_mask is not None:
            # [B, L] 1/0 -> additive [B, 1, 1, L]
            m = ops.unsqueeze(ops.unsqueeze(attention_mask, 1), 1)
            attention_mask = (1.0 - m.astype(h.dtype)) * -1e4
        seq = self.encoder(h, src_mask=attention_mask)
        return seq, self.pooler(seq)


class BertForSequenceClassification(Layer):
    """reference analog: PaddleNLP BertForSequenceClassification (GLUE)."""

    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))


class ErnieConfig(BertConfig):
    """ERNIE-base (BASELINE config 3): architecturally the BERT encoder —
    ERNIE differs in *pretraining* (knowledge/entity masking), not graph
    structure — with the ERNIE 1.0 defaults (vocab 18000, the rest
    BERT-base)."""

    def __init__(self, vocab_size=18000, **kw):
        super().__init__(vocab_size=vocab_size, **kw)


class ErnieModel(BertModel):
    """reference capability: PaddleNLP ErnieModel; same encoder graph."""

    def __init__(self, config: "ErnieConfig" = None):
        super().__init__(config or ErnieConfig())


class ErnieForSequenceClassification(BertForSequenceClassification):
    def __init__(self, config: "ErnieConfig" = None, num_classes: int = 2):
        super().__init__(config or ErnieConfig(), num_classes)
