"""paddle_tpu.models: flagship model families beyond paddle.vision.

The reference ships its NLP models through PaddleNLP (ERNIE/BERT/GPT built on
python/paddle/nn/layer/transformer.py); this package provides the same model
families natively so BASELINE configs 3 and 5 (BERT finetune, GPT hybrid
parallel) are expressible inside the framework.
"""
from .gpt import GPTConfig, GPTModel, GPTForCausalLM, GPTPretrainingCriterion  # noqa: F401
from .bert import (BertConfig, BertModel,  # noqa: F401
                   BertForSequenceClassification,
                   ErnieConfig, ErnieModel,
                   ErnieForSequenceClassification)
