"""Bench-model audit entrypoints for the trace analyzer (PTA009/PTA010).

bench.py's headline numbers come from the fused hapi train step over
ResNet-50 and GPT; these factories register *miniature* builds of those
exact step paths (same Model._build_train_step machinery, same loss and
optimizer families, shrunk shapes) so the trace audit — and the
``--bench-check`` gate over ``bench_audit_baseline.json`` — watches the
programs the benchmark actually runs. A fusion break or host transfer
introduced anywhere in the conv/BN or decoder-block step path shows up
here long before a TPU run does.

Shapes are deliberately tiny: the audit traces and XLA-compiles each
program on CPU, and the gate runs in CI.
"""
from __future__ import annotations


def _train_step_spec(build):
    """Common AuditSpec assembly over a (net, opt, loss_layer, x, y)
    bundle: mirrors hapi.model._audit_hapi_train_spec — build the fused
    train step for the signature, snapshot init params/opt state on the
    host once, and rebuild fresh donated argument arrays per call."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from ..core import audit
    from ..core.tensor import stable_uid
    from ..hapi import Model

    net, opt, loss_layer, x_np, y_np = build()
    model = Model(net)
    model.prepare(optimizer=opt, loss=loss_layer)
    sig = (((tuple(x_np.shape), str(x_np.dtype)),
            (tuple(y_np.shape), str(y_np.dtype))), False)
    ts = model._get_train_step(sig)
    for p in ts["trainable"]:
        if stable_uid(p) not in opt._state:
            opt._state[stable_uid(p)] = opt._init_state(p)
    base_train = [np.asarray(p._data)  # noqa: PTA002 -- audit-factory setup: one-time host snapshot of the init params, not a step-path sync
                  for p in ts["trainable"]]
    base_fixed = [np.asarray(ts["state"][i]._data)  # noqa: PTA002 -- audit-factory setup: one-time host snapshot, not a step-path sync
                  for i in ts["fixed_pos"]]
    base_states = jax.tree_util.tree_map(
        np.asarray, [opt._state[stable_uid(p)] for p in ts["trainable"]])

    def make_args(variant):
        # fresh arrays per call: donate_argnums=(0, 2) consumes them
        rng = np.random.default_rng(11 + variant)
        train_raws = [jnp.asarray(b) for b in base_train]
        fixed_raws = [jnp.asarray(b) for b in base_fixed]
        opt_states = jax.tree_util.tree_map(jnp.asarray, base_states)
        if np.issubdtype(x_np.dtype, np.integer):
            x = rng.integers(0, int(x_np.max()) + 1,
                             x_np.shape).astype(x_np.dtype)
        else:
            x = rng.standard_normal(x_np.shape).astype(x_np.dtype)
        if np.issubdtype(y_np.dtype, np.integer):
            y = rng.integers(0, int(y_np.max()) + 1,
                             y_np.shape).astype(y_np.dtype)
        else:
            y = rng.standard_normal(y_np.shape).astype(y_np.dtype)
        key = jax.random.PRNGKey(variant)
        lr = jnp.asarray(0.1, jnp.float32)
        step_no = jnp.asarray(1.0, jnp.float32)
        return (train_raws, fixed_raws, opt_states, [jnp.asarray(x)],
                [jnp.asarray(y)], key, lr, step_no)

    return audit.AuditSpec(fn=ts["raw_step"], make_args=make_args,
                           jit_kwargs={"donate_argnums": (0, 2)})


def _audit_resnet_train_spec():
    """bench.py workload 1 (resnet50 + Momentum + CE), shrunk to
    resnet18 @ 32x32 so CPU tracing stays cheap — identical step path:
    conv/BN running stats through the effects carry, weight decay,
    momentum update."""
    import numpy as np

    def build():
        from .. import nn, optimizer as optim, seed
        from ..vision import models as vmodels
        seed(0)
        net = vmodels.resnet18(num_classes=10)
        opt = optim.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=net.parameters(),
                             weight_decay=1e-4)
        rng = np.random.RandomState(0)
        x = rng.rand(2, 3, 32, 32).astype(np.float32)
        y = rng.randint(0, 10, (2,)).astype(np.int64)
        return net, opt, nn.CrossEntropyLoss(), x, y

    return _train_step_spec(build)


def _audit_gpt_train_spec():
    """bench.py workload 5 (GPT + AdamW + pretraining criterion), shrunk
    to 2 layers / 32 hidden / seq 32 — the decoder-block step path the
    S=4096 MFU number runs through (dense attention at this size; the
    flash kernel itself is pinned numerically by tests/test_tuner.py)."""
    import numpy as np

    def build():
        from .. import optimizer as optim, seed
        from . import GPTConfig, GPTForCausalLM, GPTPretrainingCriterion
        seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_heads=2, max_position_embeddings=64,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0)
        net = GPTForCausalLM(cfg)
        opt = optim.AdamW(learning_rate=1e-4, parameters=net.parameters(),
                          weight_decay=0.01)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (2, 32)).astype(np.int32)
        return net, opt, GPTPretrainingCriterion(), ids, ids.astype(
            np.int64)

    return _train_step_spec(build)


def _audit_gpt_ring_flash_spec():
    """The long-context dp×sp train path: a GPT-style decoder block whose
    attention is :func:`ring_flash_attention` with grads taken through
    the ring-flash custom_vjp backward (sequence_parallel.py). The audit
    pins the trace properties the S≥32k story depends on: both ring
    walks (forward + backward recomputation) must stay fused device
    programs with zero host transfers, zero retraces on warm steps, and
    clean parameter donation. Shapes are tiny (Tl=16 per rank — the
    kernel runs in interpret mode on CPU); the mesh adapts to the
    process's device count (dp=2 × sp=n/2 at 8 devices, 1×1 fallback)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from ..core import audit
    from ..distributed.fleet import sequence_parallel as sp

    devices = np.array(jax.devices())  # noqa: PTA002 -- host-side device-list layout at audit registration, not a step path
    n = devices.size
    dp = 2 if n >= 2 and n % 2 == 0 else 1
    spn = n // dp
    mesh = jax.sharding.Mesh(devices.reshape(dp, spn), ("dp", "sp"))
    B, H, D = 2, 2, 16
    T = 16 * spn                       # Tl = 16 rows per sp rank
    E = H * D

    def train_step(params, x, y):
        def loss_fn(ps):
            wq, wk, wv, wo, w1, w2 = ps

            def heads(w):
                return (x @ w).reshape(B, T, H, D).transpose(0, 2, 1, 3)

            o = sp.ring_flash_attention(heads(wq), heads(wk), heads(wv),
                                        mesh=mesh, axis="sp", causal=True,
                                        batch_axes="dp")
            h = x + o.transpose(0, 2, 1, 3).reshape(B, T, E) @ wo
            h = h + jax.nn.gelu(h @ w1) @ w2
            return jnp.mean((h - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return tuple(p - 0.1 * g for p, g in zip(params, grads)), loss

    def make_args(variant):
        # fresh params per call: donate_argnums=(0,) consumes them
        rng = np.random.default_rng(29 + variant)

        def w(*shape):
            return jnp.asarray(rng.standard_normal(shape) * 0.1,
                               jnp.float32)

        params = (w(E, E), w(E, E), w(E, E), w(E, E),
                  w(E, 2 * E), w(2 * E, E))
        x = jnp.asarray(rng.standard_normal((B, T, E)), jnp.float32)
        y = jnp.asarray(rng.standard_normal((B, T, E)), jnp.float32)
        return (params, x, y)

    return audit.AuditSpec(fn=train_step, make_args=make_args,
                           jit_kwargs={"donate_argnums": (0,)})


def _register_audit_entrypoints():
    from ..core import audit
    audit.register_entrypoint("resnet_train_step", _audit_resnet_train_spec,
                              tags=("train", "bench"))
    audit.register_entrypoint("gpt_train_step", _audit_gpt_train_spec,
                              tags=("train", "bench"))
    audit.register_entrypoint("gpt_ring_flash_train_step",
                              _audit_gpt_ring_flash_spec,
                              tags=("train", "bench", "distributed"))


_register_audit_entrypoints()
