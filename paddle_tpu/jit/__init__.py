"""paddle.jit: dynamic-to-static compilation + save/load.

TPU-native replacement for the reference @to_static stack
(reference: python/paddle/fluid/dygraph/jit.py:161 declarative,
dygraph_to_static/program_translator.py:233 StaticFunction, :689 ProgramCache,
partial_program.py:109 PartialProgramLayer).

Design difference: the reference REWRITES the Python AST (if→cond ops,
for→while_loop ops) then runs the rewritten code under a static Program.
Here the original Python executes under a jax trace (functionalize.py) and the
whole forward becomes ONE XLA computation; its vjp is the compiled backward.
Plain-Python `if`/`while` on tensor values is AST-converted to
ops.cond/ops.while_loop (ast_transform.py — runtime-dispatch helpers, one
convert_call level deep, reference ifelse_transformer.py semantics);
constructs the converter can't preserve (return inside a tensor branch)
keep the clear tracer error. Disable with
paddle_tpu.jit.enable_ast_conversion(False).

The cache is keyed by input signature exactly like ProgramCache
(program_translator.py:689): (shapes, dtypes, training-mode, param dtypes).
"""
from __future__ import annotations

import functools
import os
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core import dtypes as _dt
from ..core import generator as _gen
from ..ops.dispatch import apply
from ..core import autograd_engine as _ag
from ..observability import tracer as _otrace
from .functionalize import build_pure


class InputSpec:
    """reference: python/paddle/static/input_spec.py InputSpec."""

    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = list(shape) if shape is not None else None
        self.dtype = _dt.convert_dtype(dtype)
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)


def _is_float(dtype) -> bool:
    return (np.issubdtype(np.dtype(dtype), np.inexact)
            or dtype == jnp.bfloat16)


def _sig_of(args) -> Tuple:
    leaves, td = jax.tree_util.tree_flatten(
        args, is_leaf=lambda x: isinstance(x, Tensor))
    sig = []
    for l in leaves:
        if isinstance(l, Tensor):
            sig.append(("T", tuple(l.shape), str(l.dtype)))
        else:
            sig.append(("C", repr(l)))
    return tuple(sig), td


class StaticFunction:
    """A callable wrapping `fn` (a function or a Layer.forward) that executes
    as one compiled XLA program per input signature
    (reference: program_translator.py:233)."""

    def __init__(self, fn: Callable, layer=None, input_spec=None,
                 build_strategy=None):
        from . import ast_transform
        if ast_transform.ast_conversion_enabled():
            fn = ast_transform.convert_function(fn)
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._cache: Dict[Tuple, Any] = {}
        functools.update_wrapper(self, fn)

    @property
    def concrete_programs(self):
        return list(self._cache.values())

    def _params_and_buffers(self) -> List[Tensor]:
        if self._layer is None:
            return []
        ps = [p for _, p in self._layer.named_parameters()]
        bs = [b for _, b in self._layer.named_buffers()]
        return ps + bs

    def __call__(self, *args, **kwargs):
        state = self._params_and_buffers()
        mode_key = (self._layer.training if self._layer is not None else None)
        sig, _ = _sig_of(args)
        pkey = tuple(str(p.dtype) for p in state)
        key = (sig, mode_key, pkey, tuple(sorted(kwargs.items())) if kwargs else ())

        in_leaves, in_td = jax.tree_util.tree_flatten(
            args, is_leaf=lambda x: isinstance(x, Tensor))
        state_raws = [p._data for p in state]
        in_raws = [l._data if isinstance(l, Tensor) else l for l in in_leaves]
        diff_s = [i for i, p in enumerate(state)
                  if not p.stop_gradient and _is_float(p.dtype)]
        diff_i = [i for i, l in enumerate(in_leaves)
                  if isinstance(l, Tensor) and not l.stop_gradient
                  and _is_float(l.dtype)]

        entry = self._cache.get(key)
        fresh = entry is None
        if fresh:
            pure, meta = build_pure(self._fn, state)

            # fwd: one compiled XLA program (params, inputs, key) -> outs+effects
            def fwd(s_raws, i_raws, k, skw):
                i_tree = jax.tree_util.tree_unflatten(in_td, list(i_raws))
                return pure(list(s_raws), i_tree, k, skw)
            fwd_jit = jax.jit(fwd, static_argnums=(3,))

            # bwd: separate compiled program, recomputes fwd internally
            # (XLA fuses fwd+bwd into one program; the zero-recompute path is
            # the fully-fused train step used by hapi/static Executor).
            def bwd(sd_raws, id_raws, s_all, i_all, k, skw, cots):
                def f(sd, idf):
                    s_full = list(s_all)
                    for pos, r in zip(diff_s, sd):
                        s_full[pos] = r
                    i_full = list(i_all)
                    for pos, r in zip(diff_i, idf):
                        i_full[pos] = r
                    i_tree = jax.tree_util.tree_unflatten(in_td, i_full)
                    return pure(s_full, i_tree, k, skw)
                _, vjp = jax.vjp(f, list(sd_raws), list(id_raws))
                gs, gi = vjp(tuple(cots))
                return list(gs) + list(gi)
            bwd_jit = jax.jit(bwd, static_argnums=(5,))
            # py_fn: raw un-jitted fwd, kept for the trace auditor
            # (tools/analyze/trace) so it can re-jit under a trace counter
            entry = {"fwd": fwd_jit, "bwd": bwd_jit, "meta": meta,
                     "py_fn": fwd,
                     "jit_kwargs": {"static_argnums": (3,)}}
            self._cache[key] = entry
        meta = entry["meta"]

        call_key = _gen.next_key()
        skw = _HashableKwargs(kwargs) if kwargs else None
        if fresh:
            # first call on a new signature is where jax traces + lowers +
            # compiles the fwd program — stamp it on the span timeline so
            # recompile storms are visible next to train/step spans
            with _otrace.span(
                    "jit/compile",
                    {"fn": getattr(self._fn, "__name__", "fn")}):
                out_raws = entry["fwd"](state_raws, in_raws, call_key, skw)
        else:
            out_raws = entry["fwd"](state_raws, in_raws, call_key, skw)

        need_grad = _ag.is_grad_enabled() and (diff_s or diff_i)
        node = None
        if need_grad:
            diff_tensors = [state[i] for i in diff_s] + [in_leaves[i] for i in diff_i]
            bwd_jit = entry["bwd"]
            sd = [state_raws[i] for i in diff_s]
            idr = [in_raws[i] for i in diff_i]

            def vjp_fn(cots):
                return bwd_jit(sd, idr, state_raws, in_raws, call_key, skw,
                               tuple(cots))

            fwd_jit = entry["fwd"]
            n_ds = len(diff_s)

            def replay_pure(diff_raws, _other, _sr=tuple(state_raws),
                            _ir=tuple(in_raws)):
                # re-run the compiled forward as a function of the diff
                # inputs so double grad tracks them (autograd _replay_node)
                s_full = list(_sr)
                i_full = list(_ir)
                for pos, r in zip(diff_s, diff_raws[:n_ds]):
                    s_full[pos] = r
                for pos, r in zip(diff_i, diff_raws[n_ds:]):
                    i_full[pos] = r
                return tuple(fwd_jit(s_full, i_full, call_key, skw))

            node = _ag.GradNode(
                f"to_static:{getattr(self._fn, '__name__', 'fn')}",
                vjp_fn, diff_tensors,
                [(tuple(o.shape), o.dtype) for o in out_raws],
                replay=(replay_pure, ()))

        n_out = meta["n_out"]
        outs = []
        for i, o in enumerate(out_raws[:n_out]):
            t = Tensor(o, stop_gradient=(node is None or not _is_float(o.dtype)))
            if node is not None and _is_float(o.dtype):
                t._grad_node = (node, i)
            outs.append(t)
        for holder, val in zip(meta["effect_holders"], out_raws[n_out:]):
            holder._data = val
            holder._inplace_version += 1
        return jax.tree_util.tree_unflatten(meta["out_treedef"], outs)

    def rollback(self):
        return self._fn


class _HashableKwargs:
    """kwargs passed as a static argument to jit (must hash)."""

    def __init__(self, kw):
        self._kw = dict(kw)

    def __hash__(self):
        return hash(tuple(sorted((k, repr(v)) for k, v in self._kw.items())))

    def __eq__(self, other):
        return isinstance(other, _HashableKwargs) and self._kw == other._kw

    def keys(self):
        return self._kw.keys()

    def __getitem__(self, k):
        return self._kw[k]

    def items(self):
        return self._kw.items()


def to_static(function=None, input_spec=None, build_strategy=None, **kwargs):
    """@paddle.jit.to_static parity (reference: jit/__init__.py:22)."""
    from ..nn.layer_base import Layer

    def decorate(fn):
        if isinstance(fn, Layer):
            layer = fn
            orig_forward = layer.forward  # bound method, captured BEFORE rebind
            sf = StaticFunction(orig_forward, layer=layer, input_spec=input_spec)
            layer.forward = sf
            return layer
        # plain function or bound method of a Layer
        layer = getattr(fn, "__self__", None)
        if layer is not None and not isinstance(layer, Layer):
            layer = None
        return StaticFunction(fn, layer=layer, input_spec=input_spec)

    if function is not None:
        return decorate(function)
    return decorate


declarative = to_static


def not_to_static(fn):
    fn._not_to_static = True
    return fn


from .ast_transform import (enable_ast_conversion,  # noqa: E402,F401
                            ast_conversion_enabled, convert_function)


# ---------------------------------------------------------------------------
# save / load: serialize a compiled inference program via jax.export
# (reference: fluid/dygraph/jit.py:508 jit.save → save_inference_model;
# the saved artifact here is StableHLO + params, loadable without Python
# model code — the same deployment property as the reference's ProgramDesc.)

def save(layer, path, input_spec=None, **config):
    from ..nn.layer_base import Layer
    from jax import export as jax_export

    if isinstance(layer, StaticFunction):
        fn, owner = layer._fn, layer._layer
    elif isinstance(layer, Layer):
        owner = layer
        fwd = layer.forward
        fn = fwd._fn if isinstance(fwd, StaticFunction) else fwd
    else:
        raise TypeError("jit.save expects a Layer or StaticFunction")

    if input_spec is None:
        raise ValueError("jit.save requires input_spec on first save")
    specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
             for s in input_spec]

    if owner is not None:
        owner.eval()
    state = ([p for _, p in owner.named_parameters()] if owner else []) + \
        ([b for _, b in owner.named_buffers()] if owner else [])
    pure, meta = build_pure(fn, state)

    key = jax.random.PRNGKey(0)

    def infer_fn(param_raws, *input_raws):
        return pure(list(param_raws), list(input_raws), key, None)

    param_avals = [jax.ShapeDtypeStruct(tuple(p.shape), p.dtype) for p in state]

    def _concrete_avals():
        return [jax.ShapeDtypeStruct(
            tuple(d if d is not None else 1 for d in s.shape), s.dtype)
            for s in specs]

    def _symbolic_avals():
        # None dims export as shape-polymorphic symbols so ONE artifact
        # serves every batch size (the serving engine's bucket set); a None
        # at axis 0 is the batch dim and shares one symbol across inputs.
        scope = jax_export.SymbolicScope()
        avals = []
        for i, s in enumerate(specs):
            if s.shape is None or all(d is not None for d in s.shape):
                avals.append(jax.ShapeDtypeStruct(
                    tuple(s.shape or ()), s.dtype))
                continue
            dims = ",".join(
                ("batch" if j == 0 else f"dyn_{i}_{j}") if d is None
                else str(d) for j, d in enumerate(s.shape))
            sym = jax_export.symbolic_shape(dims, scope=scope)
            avals.append(jax.ShapeDtypeStruct(tuple(sym), s.dtype))
        return avals

    dynamic = any(d is None for s in specs for d in (s.shape or []))
    if dynamic:
        import warnings
        try:
            exported = jax_export.export(jax.jit(infer_fn))(
                param_avals, *_symbolic_avals())
        except Exception as e:
            # models with shape-dependent Python control flow can't be
            # polymorphic; keep the historical fixed-shape (None -> 1) export
            warnings.warn(
                f"jit.save: shape-polymorphic export failed ({e!r}); "
                f"falling back to concrete shapes with None -> 1")
            exported = jax_export.export(jax.jit(infer_fn))(
                param_avals, *_concrete_avals())
    else:
        exported = jax_export.export(jax.jit(infer_fn))(
            param_avals, *_concrete_avals())

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    params_np = [np.asarray(p._data) for p in state]
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({"params": params_np,
                     "n_out": meta.get("n_out"),
                     "n_in": len(specs),
                     "out_treedef_children": None}, f, protocol=4)

    sharding = config.get("sharding")
    if sharding is not None:
        # persist the sharding spec as a JSON sidecar so a replica can
        # reconstruct NamedSharding on load without the model's Python
        # code; the loader warns-and-falls-back on mesh shape mismatch
        from ..serving.sharding import ShardingSpec, save_sidecar
        if isinstance(sharding, dict):
            sharding = ShardingSpec(
                sharding.get("mesh_axes") or {},
                sharding.get("inputs"), sharding.get("params"))
        if sharding.inputs is not None \
                and len(sharding.inputs) != len(specs):
            raise ValueError(
                f"sharding names {len(sharding.inputs)} input "
                f"PartitionSpecs but input_spec has {len(specs)} entries")
        if sharding.params is not None \
                and len(sharding.params) != len(state):
            raise ValueError(
                f"sharding names {len(sharding.params)} param "
                f"PartitionSpecs but the layer has {len(state)} "
                f"params/buffers")
        save_sidecar(path, sharding)


class TranslatedLayer:
    """Loaded inference program (reference: fluid/dygraph/io.py
    TranslatedLayer). Callable like a Layer, backed by deserialized StableHLO."""

    def __init__(self, exported, params, n_out):
        self._exported = exported
        self._params = params
        self._n_out = n_out
        self.training = False

    def __call__(self, *inputs):
        raws = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
                for i in inputs]
        out = self._exported.call(self._params, *raws)
        outs = [Tensor(o) for o in out[:self._n_out or len(out)]]
        return outs[0] if len(outs) == 1 else tuple(outs)

    def eval(self):
        return self

    def forward(self, *inputs):
        return self(*inputs)


def load(path, **config):
    from jax import export as jax_export
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path + ".pdiparams", "rb") as f:
        blob = pickle.load(f)
    params = [jnp.asarray(p) for p in blob["params"]]
    return TranslatedLayer(exported, params, blob.get("n_out"))


class TracedLayer:
    """reference: fluid/dygraph/jit.py:1104 TracedLayer — trace a dygraph
    Layer once into a compiled program; call it like the layer, and export
    with save_inference_model. Here the trace is the functionalized pure
    step compiled by jax.jit (the reference records a ProgramDesc)."""

    def __init__(self, layer, pure, meta, state, out_single):
        self._layer = layer
        self._pure = pure
        self._meta = meta
        self._state = state
        self._out_single = out_single
        import jax as _jax
        self._jitted = _jax.jit(
            lambda raws, xs, key: pure(raws, xs, key, None))

    @staticmethod
    def trace(layer, inputs):
        """Returns (outputs, traced_layer) — reference TracedLayer.trace."""
        from .functionalize import build_pure
        from ..core import generator as _gen
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        params = [p for _, p in layer.named_parameters()]
        params += [b for _, b in layer.named_buffers()]
        pure, meta = build_pure(layer.forward, params)
        raws = [p._data for p in params]
        x_raws = tuple(i._data if isinstance(i, Tensor) else jnp.asarray(i)
                       for i in inputs)
        out_raws = pure(raws, x_raws, _gen.next_key(), None)
        n_out = meta["n_out"]
        outs = [Tensor(o) for o in out_raws[:n_out]]
        single = n_out == 1
        tl = TracedLayer(layer, pure, meta, params, single)
        return (outs[0] if single else outs), tl

    def __call__(self, inputs):
        from ..core import generator as _gen
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        x_raws = tuple(i._data if isinstance(i, Tensor) else jnp.asarray(i)
                       for i in inputs)
        raws = [p._data for p in self._state]
        out = self._jitted(raws, x_raws, _gen.next_key())
        outs = [Tensor(o) for o in out[:self._meta["n_out"]]]
        return outs[0] if self._out_single else outs

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        """Export via the same StableHLO path as jit.save."""
        save(self._layer, path)


# -- reference jit/__init__.py export tail -----------------------------------

class ProgramTranslator:
    """reference: dygraph_to_static/program_translator.py
    ProgramTranslator — singleton whose enable() toggles conversion;
    here that is ast_transform.enable_ast_conversion."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def enable(self, enable_to_static=True):
        from . import ast_transform
        ast_transform.enable_ast_conversion(bool(enable_to_static))

    @property
    def enable_to_static(self):
        from . import ast_transform
        return ast_transform.ast_conversion_enabled()


_VERBOSITY = 0
_CODE_LEVEL = -1


def set_verbosity(level=0, also_to_stdout=False):
    """reference: dygraph_to_static/logging_utils.py set_verbosity —
    transformer debug logging. Level > 0 prints which functions convert."""
    global _VERBOSITY
    _VERBOSITY = int(level)


def set_code_level(level=100, also_to_stdout=False):
    """reference: set_code_level — dump transformed code. Any level > -1
    makes convert_function print the rewritten source (ast.unparse)."""
    global _CODE_LEVEL
    _CODE_LEVEL = int(level)


from . import ast_transform as dy2static  # noqa: E402,F401  (module alias)
print_function = dy2static  # legacy __future__ re-export slot in reference
