"""AST conversion of plain-Python control flow for ``@to_static``.

TPU-native analog of the reference dygraph_to_static transformer suite
(reference: fluid/dygraph/dygraph_to_static/ifelse_transformer.py:38,
loop_transformer.py, convert_call_func.py, convert_operators.py —
there a 25-module AST pipeline rewriting to Program ops; here one pass
rewriting ``if``/``while`` into runtime-dispatch helpers that fall through
to plain Python for concrete predicates and lower to ``ops.cond`` /
``ops.while_loop`` (lax.cond / lax.while_loop) when the predicate is a
traced tensor).

The rewrite (reference ifelse_transformer semantics):

    if pred:                    def __pt_true_0(x):
        x = x + 1        →          x = x + 1
    else:                           return (x,)
        x = x - 1               def __pt_false_0(x): ...
                                (x,) = __pt_if__(pred, __pt_true_0,
                                                 __pt_false_0,
                                                 __pt_args__(locals(), ('x',)))

Branch/loop functions receive the mutated names as parameters (Python
closures cannot rebind outer locals) and return them; names possibly
undefined on entry travel as an ``_Undefined`` sentinel that raises a
clear error on first use (reference: dygraph_to_static UndefinedVar).

``for`` loops convert too (reference loop_transformer.py): ``for t in
range(...)`` routes through ``__pt_for_range__`` (lax.while_loop when any
bound is traced, plain python otherwise), ``for t in seq`` through
``__pt_for_iter__`` (leading-axis iteration for tensors, the native
protocol for other iterables). ``break``/``continue`` inside converted
loops lower to per-loop flags (reference break_continue_transformer.py):
``continue`` sets a jump flag that guards the rest of the iteration,
``break`` additionally sets a sticky flag folded into the loop condition;
both guards dispatch through ``__pt_if__`` so traced jump conditions
become ``lax.cond``/masked state.

List appends in loops (reference list_transformer.py list ->
LoDTensorArray): with a STATIC trip count the loop runs the python
protocol, appends unroll under tracing and a post-loop ``stack``/
``concat`` gives the stacked-tensor result — the canonical reference
patterns work unchanged. A *data-dependent* trip count cannot grow a
python list under XLA's static-shape model (the reference's tensor-array
relies on dynamic shapes); that case raises with guidance to preallocate
(see ``_no_list_state``).

Conversion is best-effort with a guaranteed fallback: any construct the
pass cannot preserve exactly (``return``/``yield`` inside a converted
branch or loop, jumps escaping try/with, closures, unavailable source)
leaves that node — or the whole function — untouched, so behaviour
degrades to the pre-existing clear tracer error, never to silently-wrong
code. ``convert_call``-style recursion is one level deep: calls to plain
user functions are routed through ``__pt_call__`` which converts the
callee's own if/while once.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import Callable, List, Optional, Sequence, Set, Tuple

import numpy as np

_ENABLED = True


def enable_ast_conversion(flag: bool = True):
    """Globally toggle plain-Python control-flow conversion under
    to_static (reference: ProgramTranslator().enable)."""
    global _ENABLED
    _ENABLED = bool(flag)


def ast_conversion_enabled() -> bool:
    return _ENABLED


# ---------------------------------------------------------------------------
# runtime pieces

class _Undefined:
    """Sentinel for a name not yet bound when a branch/loop captures scope
    (reference: dygraph_to_static UndefinedVar). Any use raises clearly."""

    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def _raise(self, *a, **k):
        raise NameError(
            f"variable {self.name!r} is used in converted control flow "
            f"before assignment (define it before the if/while so both "
            f"paths produce it)")

    def __repr__(self):
        return f"<undefined {self.name}>"

    __call__ = __getattr__ = __add__ = __radd__ = __mul__ = __bool__ = _raise
    __sub__ = __rsub__ = __truediv__ = __getitem__ = __iter__ = _raise


def _is_traced(x):
    import jax
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        x = x._data
    return isinstance(x, jax.core.Tracer)


def _is_tensorish(x):
    from ..core.tensor import Tensor
    return isinstance(x, Tensor) or _is_traced(x)


def __pt_args__(loc: dict, names: Sequence[str]) -> tuple:
    return tuple(loc.get(n, _Undefined(n)) for n in names)


def __pt_if__(pred, true_fn, false_fn, args):
    from ..ops import control_flow
    from ..static.graph import Variable
    if isinstance(pred, Variable) or _is_tensorish(pred):
        return control_flow.cond(pred, lambda: true_fn(*args),
                                 lambda: false_fn(*args))
    return true_fn(*args) if pred else false_fn(*args)


def __pt_while__(cond_fn, body_fn, names, args):
    from ..ops import control_flow
    from ..core.tensor import Tensor
    from ..static.graph import Variable
    c = cond_fn(*args)
    if isinstance(c, Variable) or _is_tensorish(c):
        for n, a in zip(names, args):
            if isinstance(a, _Undefined):
                raise NameError(
                    f"loop variable {n!r} must be initialised before a "
                    f"tensor-condition while loop")
        _no_list_state(names, args, "tensor-condition while loop")
        out = control_flow.while_loop(cond_fn, body_fn, list(args))
        return tuple(out)
    state = list(args)
    if isinstance(c, Tensor):
        c = bool(np.asarray(c._data))
    while c:
        out = body_fn(*state)
        state = list(out) if isinstance(out, (list, tuple)) else [out]
        c = cond_fn(*state)
        if isinstance(c, Tensor):
            c = bool(np.asarray(c._data))
    return tuple(state)


def _wrap_like(raw, template):
    """Return jnp results as Tensor when the operand side was a Tensor —
    converted boolean expressions must keep the eager value type."""
    from ..core.tensor import Tensor
    if isinstance(template, Tensor):
        return Tensor(raw, stop_gradient=True)
    return raw


def __pt_not__(x):
    """``not x`` that survives traced booleans (guards emitted by the
    break/continue lowering)."""
    if _is_tensorish(x):
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        raw = x._data if isinstance(x, Tensor) else x
        return _wrap_like(jnp.logical_not(raw), x)
    return not x


def _as_bool_raw(x):
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    raw = x._data if isinstance(x, Tensor) else x
    return jnp.asarray(raw).astype(jnp.bool_)


def __pt_and__(a_thunk, b_thunk):
    """``a and b`` (reference: logical_transformer.py convert_logical_and):
    python value semantics (short-circuit, returns the operand) for
    concrete values; jnp.logical_and for traced tensors — both sides
    evaluate under tracing, mirroring the reference's converted form."""
    a = a_thunk()
    if _is_tensorish(a):
        import jax.numpy as jnp
        return _wrap_like(
            jnp.logical_and(_as_bool_raw(a), _as_bool_raw(b_thunk())), a)
    return a and b_thunk()


def __pt_or__(a_thunk, b_thunk):
    """``a or b`` (reference: logical_transformer.py convert_logical_or)."""
    a = a_thunk()
    if _is_tensorish(a):
        import jax.numpy as jnp
        return _wrap_like(
            jnp.logical_or(_as_bool_raw(a), _as_bool_raw(b_thunk())), a)
    return a or b_thunk()


def __pt_assert__(cond, msg_thunk):
    """``assert`` in converted code (reference: assert_transformer.py →
    Assert op). Concrete condition: normal python assert. Traced: XLA has
    no aborting side effect inside a compiled program — like the
    reference's GPU Assert the check is skipped at trace time (the
    static.nn.Assert facade documents the same)."""
    if _is_tensorish(cond) and _is_traced(cond):
        return
    ok = cond
    from ..core.tensor import Tensor
    if isinstance(ok, Tensor):
        ok = bool(np.asarray(ok._data).all())
    if not ok:
        # msg evaluated lazily, only on failure (python semantics)
        msg = msg_thunk()
        raise AssertionError(msg if msg is not None else "")


def __pt_loop_cond__(flag, test_thunk):
    """Loop condition with a break flag: short-circuits the real test
    away once a concrete break fired (python semantics: the test is not
    re-evaluated after ``break``); under tracing both are evaluated and
    combined with logical_and."""
    if not _is_tensorish(flag):
        if flag:
            return False
        return test_thunk()
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    t = test_thunk()
    t = t._data if isinstance(t, Tensor) else t
    f = flag._data if isinstance(flag, Tensor) else flag
    return jnp.logical_and(jnp.logical_not(f), t)


def _check_initialised(names, args, what):
    for n, a in zip(names, args):
        if isinstance(a, _Undefined):
            raise NameError(
                f"loop variable {n!r} must be initialised before a "
                f"{what} (every name assigned in the loop body becomes "
                f"part of the loop state)")


def _no_list_state(names, args, what):
    for n, a in zip(names, args):
        if isinstance(a, (list, dict, set)):
            raise TypeError(
                f"{what}: loop-carried variable {n!r} is a Python "
                f"{type(a).__name__}, which cannot grow across a "
                f"data-dependent (tensor-bound) loop under XLA. Keep the "
                f"trip count static (plain-int range) so appends unroll "
                f"and stack, or preallocate a Tensor and update slices. "
                f"(The reference's list->LoDTensorArray rewrite, "
                f"list_transformer.py, relies on dynamic shapes that "
                f"have no XLA equivalent — see the module docstring.)")


def _concrete_flag(x):
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        x = x._data
    return bool(np.asarray(x))


def __pt_for_range__(start, stop, step, tgt_idx, brk_idx, body_fn, names,
                     args):
    """``for target in range(...)`` harness (reference:
    loop_transformer.py for->while rewrite). body_fn(i, *state) -> state;
    the generated prologue rebinds the target from ``i`` each iteration,
    so target rebinding inside the body does not affect iteration —
    python semantics preserved."""
    traced_bounds = any(_is_traced(v) for v in (start, stop, step))
    # a tensor break flag needs the dynamic loop even with static bounds
    dynamic = traced_bounds or (
        brk_idx >= 0 and any(_is_traced(a) for a in args))
    if dynamic:
        from ..ops import control_flow
        from ..core.tensor import Tensor
        import jax.numpy as jnp
        if isinstance(args[tgt_idx], _Undefined):
            # the loop target needs no prior binding: the body prologue
            # assigns it before any use; seed its carry slot with start
            args = list(args)
            args[tgt_idx] = start
        _check_initialised(names, args, "tensor-bound for loop")
        _no_list_state(names, args, "tensor-bound for loop")

        def raw(v):
            return v._data if isinstance(v, Tensor) else v
        start_r = jnp.asarray(raw(start))
        stop_r, step_r = raw(stop), raw(step)

        def cond(i, *s):
            ir = raw(i)
            in_r = jnp.where(jnp.asarray(step_r) > 0,
                             ir < stop_r, ir > stop_r)
            if brk_idx >= 0:
                in_r = jnp.logical_and(in_r,
                                       jnp.logical_not(raw(s[brk_idx])))
            return in_r

        def body(i, *s):
            out = body_fn(i, *s)
            return [raw(i) + step_r] + list(out)

        out = control_flow.while_loop(cond, body, [start_r] + list(args))
        return tuple(out[1:])

    def as_int(v):
        from ..core.tensor import Tensor
        if isinstance(v, Tensor) or hasattr(v, "shape"):
            return int(np.asarray(v._data if isinstance(v, Tensor) else v))
        return int(v)
    state = list(args)
    for i in range(as_int(start), as_int(stop), as_int(step)):
        state = list(body_fn(i, *state))
        if brk_idx >= 0:
            flag = state[brk_idx]
            if _is_traced(flag):
                raise TypeError(
                    "break on a traced tensor condition inside a "
                    "static-bound loop whose state is untraced — "
                    "initialise the loop-carried variables as tensors so "
                    "the loop can lower to lax.while_loop")
            if _concrete_flag(flag):
                break
    return tuple(state)


def __pt_for_iter__(seq, tgt_idx, brk_idx, body_fn, names, args):
    """``for target in seq`` harness. Tensor seq iterates its leading
    axis (reference: loop_transformer + convert_operators len/getitem);
    any other iterable (list, zip, dict, generator) runs the plain
    python protocol with the lowered body."""
    from ..core.tensor import Tensor
    if isinstance(seq, Tensor) or _is_traced(seq) or (
            hasattr(seq, "shape") and hasattr(seq, "dtype")):
        n = int(seq.shape[0])
        elem = lambda i: seq[i]
        if _is_traced(seq) and brk_idx >= 0:
            import jax.numpy as jnp
            from ..ops import control_flow
            if isinstance(args[tgt_idx], _Undefined) and n > 0:
                args = list(args)
                args[tgt_idx] = elem(0)
            _check_initialised(names, args, "tensor-bound for loop")
            _no_list_state(names, args, "tensor-bound for loop")
            raw = lambda v: v._data if isinstance(v, Tensor) else v

            def cond(i, *s):
                in_r = raw(i) < n
                return jnp.logical_and(
                    in_r, jnp.logical_not(raw(s[brk_idx])))

            def body(i, *s):
                out = body_fn(elem(i), *s)
                return [raw(i) + 1] + list(out)
            out = control_flow.while_loop(
                cond, body, [jnp.asarray(0)] + list(args))
            return tuple(out[1:])
        state = list(args)
        for i in range(n):
            state = list(body_fn(elem(i), *state))
            if brk_idx >= 0:
                flag = state[brk_idx]
                if _is_traced(flag):
                    raise TypeError(
                        "break on a traced tensor condition while "
                        "iterating a concrete tensor — pass the sequence "
                        "as a traced input so the loop lowers to "
                        "lax.while_loop")
                if _concrete_flag(flag):
                    break
        return tuple(state)
    state = list(args)
    for v in seq:
        state = list(body_fn(v, *state))
        if brk_idx >= 0:
            flag = state[brk_idx]
            if _is_traced(flag):
                raise TypeError(
                    "break on a traced tensor condition while iterating a "
                    "python sequence — the trip count is python-static "
                    "but the break is data-dependent, which cannot be "
                    "decided at trace time. Stack the sequence into a "
                    "Tensor (so the loop lowers to lax.while_loop) or "
                    "compute the break condition from concrete values")
            if _concrete_flag(flag):
                break
    return tuple(state)


_SKIP_MODULE_PREFIXES = ("paddle_tpu", "jax", "numpy", "builtins", "torch",
                         "flax", "optax")


def __pt_call__(fn, *args, **kwargs):
    """convert_call one level deep (reference: convert_call_func.py):
    a plain user function called from converted code gets its own
    if/while converted (without further call recursion). The converted
    form is memoised on the function object itself so it is evicted
    with it."""
    f = getattr(fn, "__func__", fn)
    if not isinstance(f, types.FunctionType):
        return fn(*args, **kwargs)
    mod = getattr(f, "__module__", "") or ""
    if (any(mod.startswith(p) for p in _SKIP_MODULE_PREFIXES)
            or getattr(f, "_not_to_static", False)
            or getattr(f, "__pt_converted__", False)):
        return fn(*args, **kwargs)
    conv = f.__dict__.get("__pt_call_conv__")
    if conv is None:
        conv = convert_function(f, convert_calls=False)
        f.__pt_call_conv__ = conv
    if fn is not f:  # bound method: re-bind
        return conv(fn.__self__, *args, **kwargs)
    return conv(*args, **kwargs)


_HELPERS = {
    "__pt_if__": __pt_if__,
    "__pt_while__": __pt_while__,
    "__pt_args__": __pt_args__,
    "__pt_call__": __pt_call__,
    "__pt_not__": __pt_not__,
    "__pt_and__": __pt_and__,
    "__pt_or__": __pt_or__,
    "__pt_assert__": __pt_assert__,
    "__pt_loop_cond__": __pt_loop_cond__,
    "__pt_for_range__": __pt_for_range__,
    "__pt_for_iter__": __pt_for_iter__,
}


# ---------------------------------------------------------------------------
# analysis

def _assigned_names(stmts) -> Set[str]:
    """Names (re)bound anywhere in the statement list, excluding nested
    function/class scopes."""
    out: Set[str] = set()

    def targets(t):
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets(e)
        elif isinstance(t, ast.Starred):
            targets(t.value)

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            out.add(node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            out.add(node.name)

        def visit_Lambda(self, node):
            pass

        def visit_Assign(self, node):
            for t in node.targets:
                targets(t)
            self.generic_visit(node)  # walrus bindings inside the value

        def visit_Import(self, node):
            for a in node.names:
                out.add((a.asname or a.name).split(".")[0])

        def visit_ImportFrom(self, node):
            for a in node.names:
                if a.name != "*":
                    out.add(a.asname or a.name)

        def visit_AugAssign(self, node):
            targets(node.target)
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            if node.value is not None:
                targets(node.target)
            self.generic_visit(node)

        def visit_For(self, node):
            targets(node.target)
            self.generic_visit(node)

        def visit_With(self, node):
            for item in node.items:
                if item.optional_vars is not None:
                    targets(item.optional_vars)
            self.generic_visit(node)

        def visit_NamedExpr(self, node):
            targets(node.target)
            self.generic_visit(node)

    v = V()
    for s in stmts:
        v.visit(s)
    return {n for n in out if not n.startswith("__pt_")}


_MUTATORS = {"append", "extend", "insert", "pop", "remove", "clear",
             "add", "update", "setdefault"}


def _mutated_containers(stmts) -> Set[str]:
    """Names whose containers are mutated in place via method calls
    (``xs.append(v)`` — reference list_transformer's list-op tracking).
    These must join the loop state so the dynamic-loop guard can reject
    python containers with a clear message instead of silently leaking a
    traced element out of the loop body."""
    out: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

        def visit_Call(self, node):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS
                    and isinstance(f.value, ast.Name)):
                out.add(f.value.id)
            self.generic_visit(node)

    v = V()
    for s in stmts:
        v.visit(s)
    return {n for n in out if not n.startswith("__pt_")}


def _has_escape(stmts, *, through_loops: bool) -> bool:
    """True if a return/break/continue at this control level would escape
    the extracted function. Does not descend into nested function defs;
    descends into loops only when ``through_loops`` (a break inside a
    nested loop belongs to that loop)."""
    for s in stmts:
        if isinstance(s, ast.Return):
            return True
        if isinstance(s, (ast.Break, ast.Continue)):
            return True
        if _contains_yield([s]):
            # a yield/await moved into an extracted nested function would
            # silently turn the branch into a never-consumed generator
            return True
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        if isinstance(s, getattr(ast, "Match", ())):
            return True  # conservative: match capture/return analysis n/a
        if isinstance(s, (ast.For, ast.While, ast.AsyncFor)):
            # returns still escape from inside a nested loop
            if _contains_return(list(s.body) + list(s.orelse)):
                return True
            continue
        for field in ("body", "orelse", "finalbody", "handlers"):
            sub = getattr(s, field, None)
            if sub:
                items = []
                for x in sub:
                    if isinstance(x, ast.excepthandler):
                        items.extend(x.body)
                    else:
                        items.append(x)
                if _has_escape(items, through_loops=through_loops):
                    return True
    return False


def _contains_yield(stmts) -> bool:
    """yield / yield-from / await at any depth, excluding nested function
    scopes (they establish their own generator frame)."""

    class V(ast.NodeVisitor):
        found = False

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

        def visit_Yield(self, node):
            self.found = True

        def visit_YieldFrom(self, node):
            self.found = True

        def visit_Await(self, node):
            self.found = True

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _contains_jump(stmts) -> bool:
    """Break/Continue at any depth, excluding nested loops and function
    scopes (those own their jumps)."""

    class V(ast.NodeVisitor):
        found = False

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

        def visit_For(self, node):
            pass

        visit_AsyncFor = visit_While = visit_For

        def visit_Break(self, node):
            self.found = True

        def visit_Continue(self, node):
            self.found = True

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _contains_return(stmts) -> bool:
    """Return statements at any depth, excluding nested function scopes
    (a proper recursive visitor — ast.walk's flat BFS cannot prune)."""

    class V(ast.NodeVisitor):
        found = False

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

        def visit_Return(self, node):
            self.found = True

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


# ---------------------------------------------------------------------------
# break/continue lowering (reference: break_continue_transformer.py)

def _lower_jumps(stmts, jump_name, brk_name):
    """Rewrite ``break``/``continue`` at this loop level into flag
    assignments, guarding every statement that a jump would have skipped
    with ``if __pt_not__(jump):``. Returns (new_stmts, has_break,
    has_continue); raises _JumpLowerBail when the construct cannot be
    lowered faithfully (jump inside try/with)."""
    has = {"break": False, "continue": False}

    def assign_true(name):
        return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                          value=ast.Constant(value=True))

    def rewrite(block):
        """-> (new_block, may_jump)."""
        out = []
        for idx, s in enumerate(block):
            if isinstance(s, ast.Break):
                has["break"] = True
                out.append(assign_true(jump_name))
                out.append(assign_true(brk_name))
                return out, True          # rest of the block unreachable
            if isinstance(s, ast.Continue):
                has["continue"] = True
                out.append(assign_true(jump_name))
                return out, True
            if isinstance(s, ast.If):
                nb, jb = rewrite(list(s.body))
                no, jo = rewrite(list(s.orelse))
                s = ast.If(test=s.test, body=nb, orelse=no)
                out.append(s)
                if jb or jo:
                    rest, _ = rewrite(block[idx + 1:])
                    if rest:
                        out.append(ast.If(
                            test=ast.Call(
                                func=ast.Name(id="__pt_not__",
                                              ctx=ast.Load()),
                                args=[ast.Name(id=jump_name,
                                               ctx=ast.Load())],
                                keywords=[]),
                            body=rest, orelse=[]))
                    return out, True
                continue
            if isinstance(s, (ast.For, ast.While, ast.AsyncFor,
                              ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                out.append(s)             # inner loops own their jumps
                continue
            if isinstance(s, (ast.Try, ast.With, ast.AsyncWith)) and \
                    _has_escape([s], through_loops=False):
                raise _JumpLowerBail()
            if isinstance(s, getattr(ast, "Match", ())) and \
                    _contains_jump([s]):
                raise _JumpLowerBail()    # jumps inside match-cases are
                                          # not analysed — bail cleanly
            out.append(s)
        return out, False

    new_body, _ = rewrite(list(stmts))
    return new_body, has["break"], has["continue"]


class _JumpLowerBail(Exception):
    pass


# ---------------------------------------------------------------------------
# the transformer

class _CtrlFlowTransformer(ast.NodeTransformer):
    def __init__(self, convert_calls: bool):
        self._n = 0
        self._convert_calls = convert_calls

    def _uid(self):
        self._n += 1
        return self._n - 1

    # -- calls --------------------------------------------------------------
    def visit_Call(self, node):
        self.generic_visit(node)
        if not self._convert_calls or not isinstance(node.func, ast.Name):
            return node
        if node.func.id.startswith("__pt_"):
            return node
        return ast.Call(
            func=ast.Name(id="__pt_call__", ctx=ast.Load()),
            args=[node.func] + node.args, keywords=node.keywords)

    # -- boolean operators (reference: logical_transformer.py) --------------
    @staticmethod
    def _thunk(expr):
        return ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=expr)

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        # walrus bindings would be trapped in the thunk's scope; yields/
        # awaits cannot live in a lambda — leave such BoolOps untouched
        for sub in ast.walk(node):
            if isinstance(sub, (ast.NamedExpr, ast.Yield, ast.YieldFrom,
                                ast.Await)):
                return node
        fn = "__pt_and__" if isinstance(node.op, ast.And) else "__pt_or__"
        expr = node.values[-1]
        for left in reversed(node.values[:-1]):
            expr = ast.Call(func=ast.Name(id=fn, ctx=ast.Load()),
                            args=[self._thunk(left), self._thunk(expr)],
                            keywords=[])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if not isinstance(node.op, ast.Not):
            return node
        return ast.Call(func=ast.Name(id="__pt_not__", ctx=ast.Load()),
                        args=[node.operand], keywords=[])

    # -- assert (reference: assert_transformer.py) --------------------------
    def visit_Assert(self, node):
        self.generic_visit(node)
        for sub in ast.walk(node):
            if isinstance(sub, (ast.NamedExpr, ast.Yield, ast.YieldFrom,
                                ast.Await)):
                return node
        return ast.Expr(value=ast.Call(
            func=ast.Name(id="__pt_assert__", ctx=ast.Load()),
            args=[node.test,
                  self._thunk(node.msg if node.msg is not None
                              else ast.Constant(value=None))],
            keywords=[]))

    # -- if -----------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        body, orelse = list(node.body), list(node.orelse)
        if (_has_escape(body, through_loops=False)
                or _has_escape(orelse, through_loops=False)):
            return node
        names = sorted(_assigned_names(body) | _assigned_names(orelse))
        uid = self._uid()
        tname, fname = f"__pt_true_{uid}", f"__pt_false_{uid}"
        ret = (ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
            ctx=ast.Load())) if names else ast.Return(value=None))
        tdef = self._mkfn(tname, names, body + [ret])
        fdef = self._mkfn(fname, names, (orelse or [ast.Pass()]) + [ret])
        call = ast.Call(
            func=ast.Name(id="__pt_if__", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  self._args_call(names)],
            keywords=[])
        if names:
            tail = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
                    ctx=ast.Store())],
                value=call)
        else:
            tail = ast.Expr(value=call)
        return [tdef, fdef, tail]

    # -- while --------------------------------------------------------------
    @staticmethod
    def _flag_init(name):
        return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                          value=ast.Constant(value=False))

    def visit_While(self, node):
        body = list(node.body)
        if node.orelse or _contains_return(body) or _contains_yield(body):
            self.generic_visit(node)
            return node
        uid = self._uid()
        jname, kname = f"__ptj_{uid}", f"__ptb_{uid}"
        try:
            body, has_brk, has_cont = _lower_jumps(body, jname, kname)
        except _JumpLowerBail:
            self.generic_visit(node)
            return node
        if has_brk or has_cont:
            body = [self._flag_init(jname)] + body   # per-iteration reset
        node = ast.While(test=node.test, body=body, orelse=[])
        self.generic_visit(node)     # convert nested ifs/loops + guards
        # no late bail: every escape was either pre-checked (return/yield),
        # lowered (break/continue) or bailed BEFORE mutation (_JumpLowerBail
        # on try/with/match) — returning a half-lowered loop here would
        # lose break semantics
        body = list(node.body)
        names = sorted(_assigned_names(body) | _mutated_containers(body))
        if not names:
            return node  # nothing evolves: not convertible, leave as-is
        cname, bname = f"__pt_cond_{uid}", f"__pt_body_{uid}"
        if has_brk:
            # cond = __pt_loop_cond__(brk, lambda: test): short-circuits
            # after a concrete break, logical_and under tracing
            test = ast.Call(
                func=ast.Name(id="__pt_loop_cond__", ctx=ast.Load()),
                args=[ast.Name(id=kname, ctx=ast.Load()),
                      ast.Lambda(
                          args=ast.arguments(
                              posonlyargs=[], args=[], vararg=None,
                              kwonlyargs=[], kw_defaults=[], kwarg=None,
                              defaults=[]),
                          body=node.test)],
                keywords=[])
        else:
            test = node.test
        cdef = self._mkfn(cname, names, [ast.Return(value=test)])
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
            ctx=ast.Load()))
        bdef = self._mkfn(bname, names, body + [ret])
        call = ast.Call(
            func=ast.Name(id="__pt_while__", ctx=ast.Load()),
            args=[ast.Name(id=cname, ctx=ast.Load()),
                  ast.Name(id=bname, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load()),
                  self._args_call(names)],
            keywords=[])
        tail = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
                ctx=ast.Store())],
            value=call)
        inits = ([self._flag_init(kname)] if has_brk else []) + \
                ([self._flag_init(jname)] if (has_brk or has_cont) else [])
        return inits + [cdef, bdef, tail]

    # -- for ----------------------------------------------------------------
    def visit_For(self, node):
        body = list(node.body)
        if (node.orelse or _contains_return(body) or _contains_yield(body)
                or not isinstance(node.target, ast.Name)):
            self.generic_visit(node)
            return node
        uid = self._uid()
        jname, kname = f"__ptj_{uid}", f"__ptb_{uid}"
        try:
            body, has_brk, has_cont = _lower_jumps(body, jname, kname)
        except _JumpLowerBail:
            self.generic_visit(node)
            return node
        target = node.target.id
        elem = f"__pt_elem_{uid}"
        prologue = []
        if has_brk or has_cont:
            prologue.append(self._flag_init(jname))
        prologue.append(ast.Assign(
            targets=[ast.Name(id=target, ctx=ast.Store())],
            value=ast.Name(id=elem, ctx=ast.Load())))
        # recognise `range(...)` BEFORE visiting children — visit_Call
        # would wrap it into __pt_call__(range, ...) and hide the pattern
        it = node.iter
        is_range = (isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Name)
                    and it.func.id == "range" and not it.keywords
                    and 1 <= len(it.args) <= 3
                    and not any(isinstance(a, ast.Starred)
                                for a in it.args))
        if is_range:
            if len(it.args) == 1:
                rargs = [ast.Constant(value=0), it.args[0],
                         ast.Constant(value=1)]
            elif len(it.args) == 2:
                rargs = [it.args[0], it.args[1], ast.Constant(value=1)]
            else:
                rargs = list(it.args)
            # stash the bound expressions where generic_visit still
            # converts them (nested calls etc.)
            node.iter = ast.Tuple(elts=rargs, ctx=ast.Load())
        node = ast.For(target=node.target, iter=node.iter,
                       body=prologue + body, orelse=[])
        self.generic_visit(node)     # convert nested ifs/loops + guards
        # no late bail (see visit_While): the prologue and iter rewrite are
        # already applied, so this node must complete its conversion
        body = list(node.body)
        names = sorted(_assigned_names(body) | _mutated_containers(body)
                       | {target})
        brk_idx = names.index(kname) if has_brk else -1
        tgt_idx = names.index(target)
        bname = f"__pt_forbody_{uid}"
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
            ctx=ast.Load()))
        bdef = self._mkfn(bname, [elem] + names, body + [ret])
        if is_range:
            call = ast.Call(
                func=ast.Name(id="__pt_for_range__", ctx=ast.Load()),
                args=list(node.iter.elts) + [
                    ast.Constant(value=tgt_idx),
                    ast.Constant(value=brk_idx),
                    ast.Name(id=bname, ctx=ast.Load()),
                    ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                              ctx=ast.Load()),
                    self._args_call(names)],
                keywords=[])
        else:
            call = ast.Call(
                func=ast.Name(id="__pt_for_iter__", ctx=ast.Load()),
                args=[node.iter,
                      ast.Constant(value=tgt_idx),
                      ast.Constant(value=brk_idx),
                      ast.Name(id=bname, ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                                ctx=ast.Load()),
                      self._args_call(names)],
                keywords=[])
        tail = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
                ctx=ast.Store())],
            value=call)
        inits = ([self._flag_init(kname)] if has_brk else []) + \
                ([self._flag_init(jname)] if (has_brk or has_cont) else [])
        return inits + [bdef, tail]

    # -- helpers ------------------------------------------------------------
    @staticmethod
    def _mkfn(name, params, body):
        return ast.FunctionDef(
            name=name,
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=p) for p in params],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[]),
            body=body, decorator_list=[], returns=None)

    @staticmethod
    def _args_call(names):
        return ast.Call(
            func=ast.Name(id="__pt_args__", ctx=ast.Load()),
            args=[ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                           args=[], keywords=[]),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load())],
            keywords=[])


# ---------------------------------------------------------------------------
# entry

def convert_function(fn: Callable, convert_calls: bool = True) -> Callable:
    """Return ``fn`` with plain-Python if/while converted, or ``fn``
    unchanged when conversion cannot apply (no source, closures, already
    converted). Never raises."""
    f = getattr(fn, "__func__", None)
    bound_self = getattr(fn, "__self__", None) if f is not None else None
    f = f or fn
    if not isinstance(f, types.FunctionType):
        return fn
    if inspect.isgeneratorfunction(f) or inspect.iscoroutinefunction(f) \
            or inspect.isasyncgenfunction(f):
        return fn  # generator/async frames cannot be re-sliced into cond
    if getattr(f, "__pt_converted__", False):
        return fn
    if f.__closure__:
        return fn  # recompiling would sever the closure cells
    try:
        src = textwrap.dedent(inspect.getsource(f))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []
    tr = _CtrlFlowTransformer(convert_calls)
    new_tree = tr.visit(tree)
    if tr._n == 0 and not convert_calls:
        return fn  # nothing to do
    # error source-mapping (reference: dygraph_to_static/error.py,
    # origin_info.py): the rewritten statements keep their ORIGINAL line
    # numbers; realigning to the file offset and compiling under the real
    # filename makes every traceback frame — even inside generated
    # __pt_true_*/__pt_forbody_* helpers — show the user's own source
    # line, with linecache rendering the real text
    ast.fix_missing_locations(new_tree)
    try:
        ast.increment_lineno(new_tree, f.__code__.co_firstlineno - 1)
        code = compile(new_tree, filename=f.__code__.co_filename,
                       mode="exec")
    except SyntaxError:
        return fn
    import sys
    _jit = sys.modules.get("paddle_tpu.jit")
    if _jit is not None:
        if getattr(_jit, "_VERBOSITY", 0) > 0:
            print(f"[to_static] converted {f.__qualname__} "
                  f"({tr._n} control-flow sites)")
        if getattr(_jit, "_CODE_LEVEL", -1) > -1:
            print(f"[to_static] transformed code of {f.__qualname__}:")
            print(ast.unparse(new_tree))
    glb = f.__globals__
    for k, v in _HELPERS.items():
        glb.setdefault(k, v)
    loc: dict = {}
    exec(code, glb, loc)
    new_f = loc[fdef.name]
    new_f.__defaults__ = f.__defaults__
    new_f.__kwdefaults__ = f.__kwdefaults__
    functools.update_wrapper(new_f, f)
    new_f.__pt_converted__ = True
    if bound_self is not None:
        return new_f.__get__(bound_self)
    return new_f
