"""AST conversion of plain-Python control flow for ``@to_static``.

TPU-native analog of the reference dygraph_to_static transformer suite
(reference: fluid/dygraph/dygraph_to_static/ifelse_transformer.py:38,
loop_transformer.py, convert_call_func.py, convert_operators.py —
there a 25-module AST pipeline rewriting to Program ops; here one pass
rewriting ``if``/``while`` into runtime-dispatch helpers that fall through
to plain Python for concrete predicates and lower to ``ops.cond`` /
``ops.while_loop`` (lax.cond / lax.while_loop) when the predicate is a
traced tensor).

The rewrite (reference ifelse_transformer semantics):

    if pred:                    def __pt_true_0(x):
        x = x + 1        →          x = x + 1
    else:                           return (x,)
        x = x - 1               def __pt_false_0(x): ...
                                (x,) = __pt_if__(pred, __pt_true_0,
                                                 __pt_false_0,
                                                 __pt_args__(locals(), ('x',)))

Branch/loop functions receive the mutated names as parameters (Python
closures cannot rebind outer locals) and return them; names possibly
undefined on entry travel as an ``_Undefined`` sentinel that raises a
clear error on first use (reference: dygraph_to_static UndefinedVar).

Conversion is best-effort with a guaranteed fallback: any construct the
pass cannot preserve exactly (``return``/``break``/``continue`` inside a
converted branch, closures, unavailable source) leaves that node — or the
whole function — untouched, so behaviour degrades to the pre-existing
clear tracer error, never to silently-wrong code. ``convert_call``-style
recursion is one level deep: calls to plain user functions are routed
through ``__pt_call__`` which converts the callee's own if/while once.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import Callable, List, Optional, Sequence, Set, Tuple

import numpy as np

_ENABLED = True


def enable_ast_conversion(flag: bool = True):
    """Globally toggle plain-Python control-flow conversion under
    to_static (reference: ProgramTranslator().enable)."""
    global _ENABLED
    _ENABLED = bool(flag)


def ast_conversion_enabled() -> bool:
    return _ENABLED


# ---------------------------------------------------------------------------
# runtime pieces

class _Undefined:
    """Sentinel for a name not yet bound when a branch/loop captures scope
    (reference: dygraph_to_static UndefinedVar). Any use raises clearly."""

    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def _raise(self, *a, **k):
        raise NameError(
            f"variable {self.name!r} is used in converted control flow "
            f"before assignment (define it before the if/while so both "
            f"paths produce it)")

    def __repr__(self):
        return f"<undefined {self.name}>"

    __call__ = __getattr__ = __add__ = __radd__ = __mul__ = __bool__ = _raise
    __sub__ = __rsub__ = __truediv__ = __getitem__ = __iter__ = _raise


def _is_traced(x):
    import jax
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        x = x._data
    return isinstance(x, jax.core.Tracer)


def _is_tensorish(x):
    from ..core.tensor import Tensor
    return isinstance(x, Tensor) or _is_traced(x)


def __pt_args__(loc: dict, names: Sequence[str]) -> tuple:
    return tuple(loc.get(n, _Undefined(n)) for n in names)


def __pt_if__(pred, true_fn, false_fn, args):
    from ..ops import control_flow
    from ..static.graph import Variable
    if isinstance(pred, Variable) or _is_tensorish(pred):
        return control_flow.cond(pred, lambda: true_fn(*args),
                                 lambda: false_fn(*args))
    return true_fn(*args) if pred else false_fn(*args)


def __pt_while__(cond_fn, body_fn, names, args):
    from ..ops import control_flow
    from ..core.tensor import Tensor
    from ..static.graph import Variable
    c = cond_fn(*args)
    if isinstance(c, Variable) or _is_tensorish(c):
        for n, a in zip(names, args):
            if isinstance(a, _Undefined):
                raise NameError(
                    f"loop variable {n!r} must be initialised before a "
                    f"tensor-condition while loop")
        out = control_flow.while_loop(cond_fn, body_fn, list(args))
        return tuple(out)
    state = list(args)
    if isinstance(c, Tensor):
        c = bool(np.asarray(c._data))
    while c:
        out = body_fn(*state)
        state = list(out) if isinstance(out, (list, tuple)) else [out]
        c = cond_fn(*state)
        if isinstance(c, Tensor):
            c = bool(np.asarray(c._data))
    return tuple(state)


_SKIP_MODULE_PREFIXES = ("paddle_tpu", "jax", "numpy", "builtins", "torch",
                         "flax", "optax")


def __pt_call__(fn, *args, **kwargs):
    """convert_call one level deep (reference: convert_call_func.py):
    a plain user function called from converted code gets its own
    if/while converted (without further call recursion). The converted
    form is memoised on the function object itself so it is evicted
    with it."""
    f = getattr(fn, "__func__", fn)
    if not isinstance(f, types.FunctionType):
        return fn(*args, **kwargs)
    mod = getattr(f, "__module__", "") or ""
    if (any(mod.startswith(p) for p in _SKIP_MODULE_PREFIXES)
            or getattr(f, "_not_to_static", False)
            or getattr(f, "__pt_converted__", False)):
        return fn(*args, **kwargs)
    conv = f.__dict__.get("__pt_call_conv__")
    if conv is None:
        conv = convert_function(f, convert_calls=False)
        f.__pt_call_conv__ = conv
    if fn is not f:  # bound method: re-bind
        return conv(fn.__self__, *args, **kwargs)
    return conv(*args, **kwargs)


_HELPERS = {
    "__pt_if__": __pt_if__,
    "__pt_while__": __pt_while__,
    "__pt_args__": __pt_args__,
    "__pt_call__": __pt_call__,
}


# ---------------------------------------------------------------------------
# analysis

def _assigned_names(stmts) -> Set[str]:
    """Names (re)bound anywhere in the statement list, excluding nested
    function/class scopes."""
    out: Set[str] = set()

    def targets(t):
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets(e)
        elif isinstance(t, ast.Starred):
            targets(t.value)

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            out.add(node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            out.add(node.name)

        def visit_Lambda(self, node):
            pass

        def visit_Assign(self, node):
            for t in node.targets:
                targets(t)
            self.generic_visit(node)  # walrus bindings inside the value

        def visit_Import(self, node):
            for a in node.names:
                out.add((a.asname or a.name).split(".")[0])

        def visit_ImportFrom(self, node):
            for a in node.names:
                if a.name != "*":
                    out.add(a.asname or a.name)

        def visit_AugAssign(self, node):
            targets(node.target)
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            if node.value is not None:
                targets(node.target)
            self.generic_visit(node)

        def visit_For(self, node):
            targets(node.target)
            self.generic_visit(node)

        def visit_With(self, node):
            for item in node.items:
                if item.optional_vars is not None:
                    targets(item.optional_vars)
            self.generic_visit(node)

        def visit_NamedExpr(self, node):
            targets(node.target)
            self.generic_visit(node)

    v = V()
    for s in stmts:
        v.visit(s)
    return {n for n in out if not n.startswith("__pt_")}


def _has_escape(stmts, *, through_loops: bool) -> bool:
    """True if a return/break/continue at this control level would escape
    the extracted function. Does not descend into nested function defs;
    descends into loops only when ``through_loops`` (a break inside a
    nested loop belongs to that loop)."""
    for s in stmts:
        if isinstance(s, ast.Return):
            return True
        if isinstance(s, (ast.Break, ast.Continue)):
            return True
        if _contains_yield([s]):
            # a yield/await moved into an extracted nested function would
            # silently turn the branch into a never-consumed generator
            return True
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        if isinstance(s, getattr(ast, "Match", ())):
            return True  # conservative: match capture/return analysis n/a
        if isinstance(s, (ast.For, ast.While, ast.AsyncFor)):
            # returns still escape from inside a nested loop
            if _contains_return(list(s.body) + list(s.orelse)):
                return True
            continue
        for field in ("body", "orelse", "finalbody", "handlers"):
            sub = getattr(s, field, None)
            if sub:
                items = []
                for x in sub:
                    if isinstance(x, ast.excepthandler):
                        items.extend(x.body)
                    else:
                        items.append(x)
                if _has_escape(items, through_loops=through_loops):
                    return True
    return False


def _contains_yield(stmts) -> bool:
    """yield / yield-from / await at any depth, excluding nested function
    scopes (they establish their own generator frame)."""

    class V(ast.NodeVisitor):
        found = False

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

        def visit_Yield(self, node):
            self.found = True

        def visit_YieldFrom(self, node):
            self.found = True

        def visit_Await(self, node):
            self.found = True

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _contains_return(stmts) -> bool:
    """Return statements at any depth, excluding nested function scopes
    (a proper recursive visitor — ast.walk's flat BFS cannot prune)."""

    class V(ast.NodeVisitor):
        found = False

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

        def visit_Return(self, node):
            self.found = True

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


# ---------------------------------------------------------------------------
# the transformer

class _CtrlFlowTransformer(ast.NodeTransformer):
    def __init__(self, convert_calls: bool):
        self._n = 0
        self._convert_calls = convert_calls

    def _uid(self):
        self._n += 1
        return self._n - 1

    # -- calls --------------------------------------------------------------
    def visit_Call(self, node):
        self.generic_visit(node)
        if not self._convert_calls or not isinstance(node.func, ast.Name):
            return node
        if node.func.id.startswith("__pt_"):
            return node
        return ast.Call(
            func=ast.Name(id="__pt_call__", ctx=ast.Load()),
            args=[node.func] + node.args, keywords=node.keywords)

    # -- if -----------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        body, orelse = list(node.body), list(node.orelse)
        if (_has_escape(body, through_loops=False)
                or _has_escape(orelse, through_loops=False)):
            return node
        names = sorted(_assigned_names(body) | _assigned_names(orelse))
        uid = self._uid()
        tname, fname = f"__pt_true_{uid}", f"__pt_false_{uid}"
        ret = (ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
            ctx=ast.Load())) if names else ast.Return(value=None))
        tdef = self._mkfn(tname, names, body + [ret])
        fdef = self._mkfn(fname, names, (orelse or [ast.Pass()]) + [ret])
        call = ast.Call(
            func=ast.Name(id="__pt_if__", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  self._args_call(names)],
            keywords=[])
        if names:
            tail = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
                    ctx=ast.Store())],
                value=call)
        else:
            tail = ast.Expr(value=call)
        return [tdef, fdef, tail]

    # -- while --------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        body = list(node.body)
        if node.orelse or _has_escape(body, through_loops=False):
            return node
        names = sorted(_assigned_names(body))
        if not names:
            return node  # nothing evolves: not convertible, leave as-is
        uid = self._uid()
        cname, bname = f"__pt_cond_{uid}", f"__pt_body_{uid}"
        cdef = self._mkfn(cname, names, [ast.Return(value=node.test)])
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
            ctx=ast.Load()))
        bdef = self._mkfn(bname, names, body + [ret])
        call = ast.Call(
            func=ast.Name(id="__pt_while__", ctx=ast.Load()),
            args=[ast.Name(id=cname, ctx=ast.Load()),
                  ast.Name(id=bname, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load()),
                  self._args_call(names)],
            keywords=[])
        tail = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
                ctx=ast.Store())],
            value=call)
        return [cdef, bdef, tail]

    # -- helpers ------------------------------------------------------------
    @staticmethod
    def _mkfn(name, params, body):
        return ast.FunctionDef(
            name=name,
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=p) for p in params],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[]),
            body=body, decorator_list=[], returns=None)

    @staticmethod
    def _args_call(names):
        return ast.Call(
            func=ast.Name(id="__pt_args__", ctx=ast.Load()),
            args=[ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                           args=[], keywords=[]),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load())],
            keywords=[])


# ---------------------------------------------------------------------------
# entry

def convert_function(fn: Callable, convert_calls: bool = True) -> Callable:
    """Return ``fn`` with plain-Python if/while converted, or ``fn``
    unchanged when conversion cannot apply (no source, closures, already
    converted). Never raises."""
    f = getattr(fn, "__func__", None)
    bound_self = getattr(fn, "__self__", None) if f is not None else None
    f = f or fn
    if not isinstance(f, types.FunctionType):
        return fn
    if inspect.isgeneratorfunction(f) or inspect.iscoroutinefunction(f) \
            or inspect.isasyncgenfunction(f):
        return fn  # generator/async frames cannot be re-sliced into cond
    if getattr(f, "__pt_converted__", False):
        return fn
    if f.__closure__:
        return fn  # recompiling would sever the closure cells
    try:
        src = textwrap.dedent(inspect.getsource(f))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []
    tr = _CtrlFlowTransformer(convert_calls)
    new_tree = tr.visit(tree)
    if tr._n == 0 and not convert_calls:
        return fn  # nothing to do
    ast.fix_missing_locations(new_tree)
    try:
        code = compile(new_tree, filename=f"<to_static {f.__name__} "
                       f"({f.__code__.co_filename})>", mode="exec")
    except SyntaxError:
        return fn
    glb = f.__globals__
    for k, v in _HELPERS.items():
        glb.setdefault(k, v)
    loc: dict = {}
    exec(code, glb, loc)
    new_f = loc[fdef.name]
    new_f.__defaults__ = f.__defaults__
    new_f.__kwdefaults__ = f.__kwdefaults__
    functools.update_wrapper(new_f, f)
    new_f.__pt_converted__ = True
    if bound_self is not None:
        return new_f.__get__(bound_self)
    return new_f
