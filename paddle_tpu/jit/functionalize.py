"""Functionalization: run stateful dygraph Python (Layers with mutable
Parameters, RNG draws, buffer updates) as a PURE jax-traceable function.

This is the TPU replacement for the reference's dygraph→static machinery
(reference: fluid/dygraph/dygraph_to_static/program_translator.py:582
ConcreteProgram — there, an AST-rewritten function is re-run under a static
Program; here the SAME Python runs under a jax trace with:
 - Parameters/buffers temporarily rebound to tracers (param swap)
 - RNG draws routed through a per-call key argument (so dropout masks differ
   across calls of the compiled function; the reference threads seed attrs)
 - buffer mutations (BN running stats) captured as extra outputs
   ("state effects"), applied after execution — the reference mutates
   variables in the scope directly.)
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import generator as _gen
from ..core import autograd_engine as _ag


class TraceContext:
    """Active while a stateful function is being traced to a pure one."""

    def __init__(self, key):
        self.key = key
        self.key_counter = 0
        self.state_effects: List[Tuple[Tensor, Any]] = []  # (holder, traced raw)

    def next_key(self):
        k = jax.random.fold_in(self.key, self.key_counter)
        self.key_counter += 1
        return k

    def record_effect(self, holder: Tensor, raw):
        # last write wins per holder
        for i, (h, _) in enumerate(self.state_effects):
            if h is holder:
                self.state_effects[i] = (holder, raw)
                return
        self.state_effects.append((holder, raw))


_ACTIVE: List[TraceContext] = []


def active_trace() -> Optional[TraceContext]:
    return _ACTIVE[-1] if _ACTIVE else None


@contextlib.contextmanager
def trace_context(key):
    ctx = TraceContext(key)
    _ACTIVE.append(ctx)
    # route the global generator through the trace key supply
    prev_hook = _gen._TRACE_HOOK[0]
    _gen._TRACE_HOOK[0] = ctx.next_key
    try:
        yield ctx
    finally:
        _gen._TRACE_HOOK[0] = prev_hook
        _ACTIVE.pop()


@contextlib.contextmanager
def swap_params(params: List[Tensor], raws):
    """Temporarily rebind Parameter/buffer payloads to traced values."""
    saved = [(p, p._data, p._grad_node) for p in params]
    try:
        for p, r in zip(params, raws):
            p._data = r
            p._grad_node = None
        yield
    finally:
        for p, d, n in saved:
            p._data = d
            p._grad_node = n


def build_pure(fn: Callable, params: List[Tensor], n_outputs_hint=None):
    """Return pure(param_raws, input_raws, key) -> (out_leaves, out_treedef,
    effect_raws) executing `fn` statefully but capturing all state."""

    meta = {}

    def pure(param_raws, input_raws, key, static_kwargs):
        with trace_context(key) as ctx:
            with swap_params(params, param_raws):
                with _ag.no_grad():
                    in_tensors = jax.tree_util.tree_map(
                        lambda r: Tensor(r, stop_gradient=True), input_raws)
                    out = fn(*in_tensors, **(static_kwargs or {}))
            out_leaves, out_td = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            out_raws = [o._data if isinstance(o, Tensor) else jnp.asarray(o)
                        for o in out_leaves]
            # meta is filled at trace time and read after the traced call
            meta["out_treedef"] = out_td
            meta["n_out"] = len(out_raws)
            meta["effect_holders"] = [h for h, _ in ctx.state_effects]
            effect_raws = [r for _, r in ctx.state_effects]
        return tuple(out_raws) + tuple(effect_raws)

    return pure, meta
