"""Segment reductions (reference: operators/segment_pool_op.cc:22,
python/paddle/incubate/tensor/math.py segment_sum/mean/max/min).

The reference kernel walks sorted ``segment_ids`` on CPU / uses a CUB scan
on GPU; here each reduction lowers to ``jax.ops.segment_*`` which XLA turns
into a single sorted-scatter — MXU-irrelevant but HBM-friendly (one pass).

Shape contract: ``segment_ids`` is [N] int, sorted ascending, possibly with
gaps (empty segments produce 0 for sum/mean and 0 for max/min to match the
reference's "empty segment -> 0" convention, segment_pool_op.cc
SegmentKernelLaunchHelper). The number of segments is data-dependent; under
``jit`` pass ``num_segments`` explicitly (static), in eager it is read from
the concrete ids.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .dispatch import apply, raw as _raw
from ..core.tensor import Tensor

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "segment_pool"]


def _num_segments(segment_ids, num_segments):
    if num_segments is not None:
        return int(num_segments)
    ids = _raw(segment_ids)
    if isinstance(ids, jax.core.Tracer):
        raise ValueError(
            "segment_* under jit needs a static num_segments= (the output "
            "shape is data-dependent); pass it explicitly.")
    return int(np.asarray(ids).max()) + 1 if ids.shape[0] else 0


def _segment(name, data, segment_ids, num_segments, reducer, empty_fill):
    n = _num_segments(segment_ids, num_segments)

    def impl(d, ids):
        out = reducer(d, ids, num_segments=n)
        # reference: empty segments are 0-filled, not +/-inf.
        if empty_fill is not None:
            counts = jax.ops.segment_sum(jnp.ones_like(ids, jnp.int32), ids,
                                         num_segments=n)
            shape = (n,) + (1,) * (d.ndim - 1)
            out = jnp.where(counts.reshape(shape) > 0, out, empty_fill)
        return out
    return apply(name, impl, data, segment_ids)


def segment_sum(data, segment_ids, num_segments=None, name=None):
    """reference: incubate/tensor/math.py segment_sum -> segment_pool_op
    (pooltype SUM)."""
    return _segment("segment_sum", data, segment_ids, num_segments,
                    jax.ops.segment_sum, None)


def segment_mean(data, segment_ids, num_segments=None, name=None):
    """reference: segment_pool_op (pooltype MEAN); empty segments -> 0."""
    n = _num_segments(segment_ids, num_segments)

    def impl(d, ids):
        s = jax.ops.segment_sum(d, ids, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones_like(ids, d.dtype), ids,
                                num_segments=n)
        shape = (n,) + (1,) * (d.ndim - 1)
        c = c.reshape(shape)
        return jnp.where(c > 0, s / jnp.maximum(c, 1), 0).astype(d.dtype)
    return apply("segment_mean", impl, data, segment_ids)


def segment_max(data, segment_ids, num_segments=None, name=None):
    """reference: segment_pool_op (pooltype MAX); empty segments -> 0."""
    return _segment("segment_max", data, segment_ids, num_segments,
                    jax.ops.segment_max, 0)


def segment_min(data, segment_ids, num_segments=None, name=None):
    """reference: segment_pool_op (pooltype MIN); empty segments -> 0."""
    return _segment("segment_min", data, segment_ids, num_segments,
                    jax.ops.segment_min, 0)


_POOLS = {"SUM": segment_sum, "MEAN": segment_mean, "MAX": segment_max,
          "MIN": segment_min}


def segment_pool(data, segment_ids, pooltype="SUM", num_segments=None,
                 name=None):
    """The raw op facade (reference: segment_pool_op.cc:22 attr
    ``pooltype``)."""
    try:
        fn = _POOLS[pooltype.upper()]
    except KeyError:
        raise ValueError(f"segment_pool: unknown pooltype {pooltype!r}; "
                         f"one of {sorted(_POOLS)}")
    return fn(data, segment_ids, num_segments=num_segments)
