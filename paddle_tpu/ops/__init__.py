"""Functional op library + Tensor method attachment.

The reference generates per-op Python fast-path entry points at build time
(pybind/op_function_generator.cc:496 → core.ops.*) and patches methods onto
VarBase (python/paddle/fluid/dygraph/varbase_patch_methods.py). Here the ops
are plain Python functions over traceable jnp implementations, and Tensor
methods are attached from a table at import time.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import creation, math, manipulation, linalg, dispatch
from .dispatch import (apply, apply_raw, OP_REGISTRY, in_dygraph_mode,
                       enable_static, disable_static)

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .control_flow import (cond, while_loop, case, switch_case,  # noqa: F401
                           increment, create_array, array_write, array_read,
                           array_length)
from .detection import (yolo_box, yolov3_loss, multiclass_nms,  # noqa: F401
                        prior_box, box_coder, iou_similarity, box_clip)


def _attach_methods():
    m, mp, cr = math, manipulation, creation

    methods = {
        # math
        "abs": m.abs, "exp": m.exp, "log": m.log, "log2": m.log2,
        "log10": m.log10, "log1p": m.log1p, "sqrt": m.sqrt, "rsqrt": m.rsqrt,
        "square": m.square, "sin": m.sin, "cos": m.cos, "tan": m.tan,
        "tanh": m.tanh, "sigmoid": m.sigmoid, "floor": m.floor, "ceil": m.ceil,
        "round": m.round, "trunc": m.trunc, "sign": m.sign,
        "reciprocal": m.reciprocal, "erf": m.erf, "erfinv": m.erfinv,
        "lgamma": m.lgamma, "digamma": m.digamma, "neg": m.neg,
        "isnan": m.isnan, "isinf": m.isinf, "isfinite": m.isfinite,
        "logical_not": m.logical_not, "bitwise_not": m.bitwise_not,
        "add": m.add, "subtract": m.subtract, "multiply": m.multiply,
        "divide": m.divide, "floor_divide": m.floor_divide, "mod": m.mod,
        "remainder": m.remainder, "pow": m.pow, "maximum": m.maximum,
        "minimum": m.minimum, "fmax": m.fmax, "fmin": m.fmin,
        "atan2": m.atan2, "logical_and": m.logical_and,
        "logical_or": m.logical_or, "logical_xor": m.logical_xor,
        "bitwise_and": m.bitwise_and, "bitwise_or": m.bitwise_or,
        "bitwise_xor": m.bitwise_xor, "equal": m.equal,
        "not_equal": m.not_equal, "greater_than": m.greater_than,
        "greater_equal": m.greater_equal, "less_than": m.less_than,
        "less_equal": m.less_equal, "equal_all": m.equal_all,
        "allclose": m.allclose, "isclose": m.isclose,
        "matmul": m.matmul, "mm": m.mm, "bmm": m.bmm, "mv": m.mv,
        "dot": m.dot, "inner": m.inner, "outer": m.outer, "kron": m.kron,
        "cross": m.cross, "trace": m.trace, "scale": m.scale, "clip": m.clip,
        "lerp": m.lerp, "nan_to_num": m.nan_to_num,
        # reductions
        "sum": m.sum, "mean": m.mean, "prod": m.prod, "max": m.max,
        "min": m.min, "amax": m.amax, "amin": m.amin, "all": m.all,
        "any": m.any, "std": m.std, "var": m.var, "median": m.median,
        "nanmean": m.nanmean, "nansum": m.nansum, "quantile": m.quantile,
        "logsumexp": m.logsumexp, "cumsum": m.cumsum, "cumprod": m.cumprod,
        "count_nonzero": m.count_nonzero, "norm": m.norm, "dist": m.dist,
        # search/sort
        "argmax": m.argmax, "argmin": m.argmin, "argsort": m.argsort,
        "sort": m.sort, "topk": m.topk, "kthvalue": m.kthvalue, "mode": m.mode,
        "where": m.where, "nonzero": m.nonzero, "masked_select": m.masked_select,
        "masked_fill": m.masked_fill, "index_select": m.index_select,
        "index_sample": m.index_sample, "take_along_axis": m.take_along_axis,
        "put_along_axis": m.put_along_axis, "gather": m.gather,
        "gather_nd": m.gather_nd, "scatter": m.scatter,
        "scatter_nd_add": m.scatter_nd_add, "bincount": m.bincount,
        "histogram": m.histogram, "unique": m.unique,
        "unique_consecutive": m.unique_consecutive,
        "searchsorted": m.searchsorted,
        # manipulation
        "reshape": mp.reshape, "reshape_": mp.reshape_,
        "transpose": mp.transpose, "moveaxis": mp.moveaxis,
        "swapaxes": mp.swapaxes, "split": mp.split, "chunk": mp.chunk,
        "squeeze": mp.squeeze, "squeeze_": mp.squeeze_,
        "unsqueeze": mp.unsqueeze, "unsqueeze_": mp.unsqueeze_,
        "flatten": mp.flatten, "tile": mp.tile, "expand": mp.expand,
        "expand_as": mp.expand_as, "broadcast_to": mp.broadcast_to,
        "flip": mp.flip, "roll": mp.roll, "unbind": mp.unbind,
        "unstack": mp.unstack, "repeat_interleave": mp.repeat_interleave,
        "slice": mp.slice, "strided_slice": mp.strided_slice,
        "tolist": mp.tolist, "tensordot": mp.tensordot,
        # linalg
        "cholesky": linalg.cholesky, "inverse": linalg.inv,
        "matrix_power": linalg.matrix_power,
        # creation-ish
        "fill_": creation.fill_, "zero_": creation.zero_,
        "uniform_": creation.uniform_, "normal_": creation.normal_,
    }
    for name, fn in methods.items():
        setattr(Tensor, name, fn)

    # operator dunders
    def _rsub(x, y):
        return m.subtract(creation.to_tensor(y) if not isinstance(y, Tensor) else y, x)

    def _rdiv(x, y):
        return m.divide(creation.to_tensor(y) if not isinstance(y, Tensor) else y, x)

    def _rpow(x, y):
        return m.pow(creation.to_tensor(y) if not isinstance(y, Tensor) else y, x)

    def _rmod(x, y):
        return m.mod(creation.to_tensor(y) if not isinstance(y, Tensor) else y, x)

    dunders = {
        "__add__": m.add, "__radd__": m.add, "__sub__": m.subtract,
        "__rsub__": _rsub, "__mul__": m.multiply, "__rmul__": m.multiply,
        "__truediv__": m.divide, "__rtruediv__": _rdiv,
        "__floordiv__": m.floor_divide, "__mod__": m.mod, "__rmod__": _rmod,
        "__pow__": m.pow, "__rpow__": _rpow, "__matmul__": m.matmul,
        "__neg__": m.neg, "__abs__": m.abs,
        "__eq__": m.equal, "__ne__": m.not_equal, "__gt__": m.greater_than,
        "__ge__": m.greater_equal, "__lt__": m.less_than,
        "__le__": m.less_equal, "__invert__": m.logical_not,
        "__and__": m.bitwise_and, "__or__": m.bitwise_or,
        "__xor__": m.bitwise_xor,
    }
    for name, fn in dunders.items():
        setattr(Tensor, name, fn)

    @property
    def T(self):
        return mp.transpose(self, list(range(self.ndim))[::-1])
    Tensor.T = T


_attach_methods()
