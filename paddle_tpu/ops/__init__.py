"""Functional op library + Tensor method attachment.

The reference generates per-op Python fast-path entry points at build time
(pybind/op_function_generator.cc:496 → core.ops.*) and patches methods onto
VarBase (python/paddle/fluid/dygraph/varbase_patch_methods.py). Here the ops
are plain Python functions over traceable jnp implementations, and Tensor
methods are attached from a table at import time.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import creation, math, manipulation, linalg, dispatch, custom
from .dispatch import (apply, apply_raw, OP_REGISTRY, in_dygraph_mode,
                       enable_static, disable_static)

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .control_flow import (cond, while_loop, case, switch_case,  # noqa: F401
                           increment, create_array, array_write, array_read,
                           array_length)
from .detection import (yolo_box, yolov3_loss, multiclass_nms,  # noqa: F401
                        prior_box, box_coder, iou_similarity, box_clip,
                        roi_align, roi_pool, anchor_generator,
                        generate_proposals, distribute_fpn_proposals,
                        collect_fpn_proposals, bipartite_match,
                        target_assign, box_decoder_and_assign,
                        polygon_box_transform, smooth_l1, matrix_nms,
                        density_prior_box, psroi_pool, prroi_pool,
                        deformable_psroi_pooling)
from .segment import (segment_sum, segment_mean, segment_max,  # noqa: F401
                      segment_min, segment_pool)
from .extras import *  # noqa: F401,F403
from .crf import (linear_chain_crf, crf_decoding, viterbi_decode,  # noqa: F401
                  chunk_eval)
from .pallas_attention import flash_attention  # noqa: F401
from .sequence import (sequence_mask, sequence_pad, sequence_unpad,  # noqa: F401
                       sequence_pool, sequence_first_step,
                       sequence_last_step, sequence_softmax,
                       sequence_reverse, sequence_expand,
                       sequence_expand_as, sequence_concat, sequence_slice,
                       sequence_enumerate, sequence_erase, sequence_conv,
                       im2sequence)
from .beam import (gather_tree, beam_search, beam_search_decode,  # noqa: F401
                   ctc_align, edit_distance)


def _attach_methods():
    m, mp, cr = math, manipulation, creation

    methods = {
        # math
        "abs": m.abs, "exp": m.exp, "log": m.log, "log2": m.log2,
        "log10": m.log10, "log1p": m.log1p, "sqrt": m.sqrt, "rsqrt": m.rsqrt,
        "square": m.square, "sin": m.sin, "cos": m.cos, "tan": m.tan,
        "tanh": m.tanh, "sigmoid": m.sigmoid, "floor": m.floor, "ceil": m.ceil,
        "round": m.round, "trunc": m.trunc, "sign": m.sign,
        "reciprocal": m.reciprocal, "erf": m.erf, "erfinv": m.erfinv,
        "lgamma": m.lgamma, "digamma": m.digamma, "neg": m.neg,
        "isnan": m.isnan, "isinf": m.isinf, "isfinite": m.isfinite,
        "logical_not": m.logical_not, "bitwise_not": m.bitwise_not,
        "add": m.add, "subtract": m.subtract, "multiply": m.multiply,
        "divide": m.divide, "floor_divide": m.floor_divide, "mod": m.mod,
        "remainder": m.remainder, "pow": m.pow, "maximum": m.maximum,
        "minimum": m.minimum, "fmax": m.fmax, "fmin": m.fmin,
        "atan2": m.atan2, "logical_and": m.logical_and,
        "logical_or": m.logical_or, "logical_xor": m.logical_xor,
        "bitwise_and": m.bitwise_and, "bitwise_or": m.bitwise_or,
        "bitwise_xor": m.bitwise_xor, "equal": m.equal,
        "not_equal": m.not_equal, "greater_than": m.greater_than,
        "greater_equal": m.greater_equal, "less_than": m.less_than,
        "less_equal": m.less_equal, "equal_all": m.equal_all,
        "allclose": m.allclose, "isclose": m.isclose,
        "matmul": m.matmul, "mm": m.mm, "bmm": m.bmm, "mv": m.mv,
        "dot": m.dot, "inner": m.inner, "outer": m.outer, "kron": m.kron,
        "cross": m.cross, "trace": m.trace, "scale": m.scale, "clip": m.clip,
        "lerp": m.lerp, "nan_to_num": m.nan_to_num,
        # reductions
        "sum": m.sum, "mean": m.mean, "prod": m.prod, "max": m.max,
        "min": m.min, "amax": m.amax, "amin": m.amin, "all": m.all,
        "any": m.any, "std": m.std, "var": m.var, "median": m.median,
        "nanmean": m.nanmean, "nansum": m.nansum, "quantile": m.quantile,
        "logsumexp": m.logsumexp, "cumsum": m.cumsum, "cumprod": m.cumprod,
        "count_nonzero": m.count_nonzero, "norm": m.norm, "dist": m.dist,
        # search/sort
        "argmax": m.argmax, "argmin": m.argmin, "argsort": m.argsort,
        "sort": m.sort, "topk": m.topk, "kthvalue": m.kthvalue, "mode": m.mode,
        "where": m.where, "nonzero": m.nonzero, "masked_select": m.masked_select,
        "masked_fill": m.masked_fill, "index_select": m.index_select,
        "index_sample": m.index_sample, "take_along_axis": m.take_along_axis,
        "put_along_axis": m.put_along_axis, "gather": m.gather,
        "gather_nd": m.gather_nd, "scatter": m.scatter,
        "scatter_nd_add": m.scatter_nd_add, "bincount": m.bincount,
        "histogram": m.histogram, "unique": m.unique,
        "unique_consecutive": m.unique_consecutive,
        "searchsorted": m.searchsorted,
        # manipulation
        "reshape": mp.reshape, "reshape_": mp.reshape_,
        "transpose": mp.transpose, "moveaxis": mp.moveaxis,
        "swapaxes": mp.swapaxes, "split": mp.split, "chunk": mp.chunk,
        "squeeze": mp.squeeze, "squeeze_": mp.squeeze_,
        "unsqueeze": mp.unsqueeze, "unsqueeze_": mp.unsqueeze_,
        "flatten": mp.flatten, "tile": mp.tile, "expand": mp.expand,
        "expand_as": mp.expand_as, "broadcast_to": mp.broadcast_to,
        "flip": mp.flip, "roll": mp.roll, "unbind": mp.unbind,
        "unstack": mp.unstack, "repeat_interleave": mp.repeat_interleave,
        "slice": mp.slice, "strided_slice": mp.strided_slice,
        "tolist": mp.tolist, "tensordot": mp.tensordot,
        # linalg
        "cholesky": linalg.cholesky, "inverse": linalg.inv,
        "matrix_power": linalg.matrix_power,
        # creation-ish
        "fill_": creation.fill_, "zero_": creation.zero_,
        "uniform_": creation.uniform_, "normal_": creation.normal_,
        # round-5 tail (reference tensor/__init__.py method list)
        "acos": m.acos, "asin": m.asin, "atan": m.atan,
        "cosh": m.cosh, "sinh": m.sinh, "conj": m.conj,
        "real": m.real, "imag": m.imag, "stanh": m.stanh,
        "addmm": m.addmm, "tril": creation.tril, "triu": creation.triu,
        "multinomial": creation.multinomial, "mul": m.multiply,
        "floor_mod": m.mod, "reverse": mp.flip,
    }
    for name, fn in methods.items():
        setattr(Tensor, name, fn)

    # shared implementations, not hand-rolled closures: the method and
    # the free function must take the same dispatch path
    Tensor.t = mp.t
    Tensor.numel = creation.numel
    Tensor.is_empty = m.is_empty

    def _rank_m(self):
        from ..tensor import rank as _rank   # deferred: circular import
        return _rank(self)
    Tensor.rank = _rank_m

    # inplace variants: functional result adopted onto the tape via
    # _swap_payload (core/tensor.py contract — grads stay correct)
    def _inplace_of(fn):
        def method(self, *a, **k):
            self._swap_payload(fn(self, *a, **k))
            return self
        return method
    for iname, ifn in {
            "add_": m.add, "subtract_": m.subtract, "scale_": m.scale,
            "clip_": m.clip, "exp_": m.exp, "sqrt_": m.sqrt,
            "rsqrt_": m.rsqrt, "reciprocal_": m.reciprocal,
            "floor_": m.floor, "ceil_": m.ceil, "round_": m.round,
            "tanh_": m.tanh, "flatten_": mp.flatten,
            "scatter_": m.scatter}.items():
        setattr(Tensor, iname, _inplace_of(ifn))

    # operator dunders
    def _rsub(x, y):
        return m.subtract(creation.to_tensor(y) if not isinstance(y, Tensor) else y, x)

    def _rdiv(x, y):
        return m.divide(creation.to_tensor(y) if not isinstance(y, Tensor) else y, x)

    def _rpow(x, y):
        return m.pow(creation.to_tensor(y) if not isinstance(y, Tensor) else y, x)

    def _rmod(x, y):
        return m.mod(creation.to_tensor(y) if not isinstance(y, Tensor) else y, x)

    dunders = {
        "__add__": m.add, "__radd__": m.add, "__sub__": m.subtract,
        "__rsub__": _rsub, "__mul__": m.multiply, "__rmul__": m.multiply,
        "__truediv__": m.divide, "__rtruediv__": _rdiv,
        "__floordiv__": m.floor_divide, "__mod__": m.mod, "__rmod__": _rmod,
        "__pow__": m.pow, "__rpow__": _rpow, "__matmul__": m.matmul,
        "__neg__": m.neg, "__abs__": m.abs,
        "__eq__": m.equal, "__ne__": m.not_equal, "__gt__": m.greater_than,
        "__ge__": m.greater_equal, "__lt__": m.less_than,
        "__le__": m.less_equal, "__invert__": m.logical_not,
        "__and__": m.bitwise_and, "__or__": m.bitwise_or,
        "__xor__": m.bitwise_xor,
    }
    for name, fn in dunders.items():
        setattr(Tensor, name, fn)

    @property
    def T(self):
        return mp.transpose(self, list(range(self.ndim))[::-1])
    Tensor.T = T


_attach_methods()


def _late_alias():
    """Expose the fluid-era op-name surface (reference: Appendix A of
    SURVEY — names registered via REGISTER_OPERATOR) for functionality that
    lives in nn.functional / ops.linalg under the 2.x API. Called from
    paddle_tpu/__init__ after nn loads (avoids the ops<->nn import cycle)."""
    import sys
    from ..nn import functional as F
    from . import linalg as L

    mod = sys.modules[__name__]
    f_names = ["relu", "relu6", "gelu", "silu", "selu", "elu", "celu",
               "mish", "swish", "softmax", "log_softmax", "leaky_relu",
               "prelu", "maxout", "softplus", "softsign", "hardshrink",
               "softshrink", "tanhshrink", "hardsigmoid", "hardswish",
               "hardtanh", "log_sigmoid", "thresholded_relu", "grid_sample",
               "affine_grid", "interpolate", "upsample", "pixel_shuffle",
               "dropout", "label_smooth", "sigmoid_focal_loss",
               "smooth_l1_loss", "kl_div", "one_hot",
               "deformable_conv"]
    for n in f_names:
        if hasattr(F, n) and not hasattr(mod, n):
            setattr(mod, n, getattr(F, n))
    # fluid spellings
    fluid_map = {"logsigmoid": "log_sigmoid", "hard_sigmoid": "hardsigmoid",
                 "hard_shrink": "hardshrink", "tanh_shrink": "tanhshrink",
                 "hard_swish": "hardswish", "brelu": "hardtanh",
                 "kldiv_loss": "kl_div"}
    for alias, src in fluid_map.items():
        if hasattr(F, src) and not hasattr(mod, alias):
            setattr(mod, alias, getattr(F, src))
    l_names = ["cholesky", "inverse", "det", "slogdet", "qr", "svd", "eig",
               "eigh", "eigvals", "eigvalsh", "matrix_power", "matrix_rank",
               "multi_dot", "pinv", "lstsq", "solve", "triangular_solve",
               "cholesky_solve", "lu", "matrix_exp"]
    for n in l_names:
        if hasattr(L, n) and not hasattr(mod, n):
            setattr(mod, n, getattr(L, n))
    # 1:1 renames of existing ops
    # fluid 'mul' is a flattened MATRIX multiply (operators/mul_op.cc)
    renames = {"arg_max": "argmax", "arg_min": "argmin", "mul": "matmul",
               "minus": "subtract", "reverse": "flip",
               "fill_constant": "full", "reduce_sum": "sum",
               "reduce_mean": "mean", "reduce_max": "max",
               "reduce_min": "min", "reduce_prod": "prod",
               "reduce_all": "all", "reduce_any": "any",
               "elementwise_add": "add", "elementwise_sub": "subtract",
               "elementwise_mul": "multiply", "elementwise_div": "divide",
               "elementwise_pow": "pow", "elementwise_max": "maximum",
               "elementwise_min": "minimum", "elementwise_mod": "mod",
               "elementwise_floordiv": "floor_divide",
               "expand_as_v2": "expand_as", "expand_v2": "expand",
               "matmul_v2": "matmul", "one_hot_v2": "one_hot",
               "p_norm": "norm", "nonzero": "nonzero"}
    for alias, src in renames.items():
        if hasattr(mod, src) and not hasattr(mod, alias):
            setattr(mod, alias, getattr(mod, src))


def _stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    """reference: activation_op.h STanhFunctor."""
    from .dispatch import apply as _apply
    import jax.numpy as _jnp
    return _apply("stanh", lambda a: scale_b * _jnp.tanh(scale_a * a), x)


def _soft_relu(x, threshold=40.0, name=None):
    """reference: activation_op.h SoftReluFunctor."""
    from .dispatch import apply as _apply
    import jax.numpy as _jnp
    return _apply("soft_relu",
                  lambda a: _jnp.log1p(_jnp.exp(
                      _jnp.clip(a, -threshold, threshold))), x)


stanh = _stanh
soft_relu = _soft_relu
