"""Operator long tail: small tensor utilities, recommender-era feature ops,
distillation/metric losses, and misc NN ops from the reference catalog
(SURVEY Appendix A) that don't belong to a bigger family module.

Everything is a fixed-shape jnp composition behind the dispatch funnel —
these ops are glue, not FLOPs; the win is that they fuse into whatever jit
region calls them instead of being standalone kernels like the reference's
per-op CUDA implementations.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .dispatch import apply, raw as _raw
from ..core.tensor import Tensor
from ..core import generator as _gen

__all__ = [
    "shape", "size", "assign_value",
    "fill_constant_batch_size_like", "uniform_random_batch_size_like",
    "gaussian_random_batch_size_like", "pad_constant_like",
    "squared_l2_distance", "bpr_loss", "modified_huber_loss",
    "teacher_student_sigmoid_loss", "center_loss", "mean_iou",
    "precision_recall", "positive_negative_pair", "affine_channel",
    "data_norm", "batch_fc", "partial_concat", "partial_sum",
    "shuffle_batch", "cvm", "filter_by_instag", "row_conv", "conv_shift",
    "add_position_encoding", "correlation", "similarity_focus", "fsp",
    "spp", "max_unpool2d", "match_matrix_tensor", "margin_rank_loss",
]


# -- tensor utilities ---------------------------------------------------------

def shape(input, name=None):
    """reference: operators/shape_op.cc — runtime shape as an int32 tensor.
    Shapes are trace-time constants under XLA, so this is a constant."""
    return apply("shape", lambda x: jnp.asarray(x.shape, jnp.int32), input)


def size(input, name=None):
    """reference: operators/size_op.cc — numel as an integer scalar (the
    framework's default int width; x64 is off under jit)."""
    return apply("size", lambda x: jnp.asarray(x.size), input)


def assign_value(shape, dtype, values, name=None):
    """reference: operators/assign_value_op.cc — materialize a constant."""
    from .creation import to_tensor
    return to_tensor(np.asarray(values, dtype).reshape(shape))


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  name=None):
    """reference: operators/fill_constant_batch_size_like_op.cc."""
    shp = list(shape)
    shp[output_dim_idx] = _raw(input).shape[input_dim_idx]

    def impl(x):
        return jnp.full(shp, value, np.dtype(dtype))
    return apply("fill_constant_batch_size_like", impl, input)


def uniform_random_batch_size_like(input, shape, low=-1.0, high=1.0,
                                   input_dim_idx=0, output_dim_idx=0,
                                   dtype="float32", name=None):
    """reference: operators/uniform_random_batch_size_like_op.cc."""
    shp = list(shape)
    shp[output_dim_idx] = _raw(input).shape[input_dim_idx]
    key = _gen.next_key()

    def impl(x):
        return jax.random.uniform(key, shp, np.dtype(dtype), low, high)
    return apply("uniform_random_batch_size_like", impl, input)


def gaussian_random_batch_size_like(input, shape, mean=0.0, std=1.0,
                                    input_dim_idx=0, output_dim_idx=0,
                                    dtype="float32", name=None):
    """reference: operators/gaussian_random_batch_size_like_op.cc."""
    shp = list(shape)
    shp[output_dim_idx] = _raw(input).shape[input_dim_idx]
    key = _gen.next_key()

    def impl(x):
        return mean + std * jax.random.normal(key, shp, np.dtype(dtype))
    return apply("gaussian_random_batch_size_like", impl, input)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """reference: operators/pad_constant_like_op.cc — pad ``y`` at the high
    end of every axis up to ``x``'s shape."""
    tgt = _raw(x).shape

    def impl(xx, yy):
        pads = [(0, int(t) - int(s)) for t, s in zip(tgt, yy.shape)]
        return jnp.pad(yy, pads, constant_values=pad_value)
    return apply("pad_constant_like", impl, x, y)


# -- losses -------------------------------------------------------------------

def squared_l2_distance(x, y, name=None):
    """reference: operators/squared_l2_distance_op.cc — rowwise ||x-y||^2,
    output [N, 1]."""
    def impl(a, b):
        d = a.reshape(a.shape[0], -1) - b.reshape(b.shape[0], -1)
        return jnp.sum(d * d, axis=1, keepdims=True)
    return apply("squared_l2_distance", impl, x, y)


def bpr_loss(input, label, name=None):
    """reference: operators/bpr_loss_op.h:70 — Bayesian Personalized
    Ranking: loss[i] = mean over j != y of softplus(x_j - x_y)."""
    def impl(lg, lab):
        n, c = lg.shape
        pos = jnp.take_along_axis(lg, lab.reshape(n, 1).astype(jnp.int32), 1)
        sp = jax.nn.softplus(lg - pos)                  # [N, C]; j==y -> log 2
        mask = jax.nn.one_hot(lab.reshape(n), c, dtype=lg.dtype)
        return (jnp.sum(sp * (1 - mask), axis=1, keepdims=True)
                / (c - 1)).astype(lg.dtype)
    return apply("bpr_loss", impl, input, label)


def modified_huber_loss(input, label, name=None):
    """reference: operators/modified_huber_loss_op.h:43 — inter = x*(2y-1);
    loss = -4*inter if inter < -1; (1-inter)^2 if inter < 1; else 0."""
    def impl(x, y):
        inter = x * (2.0 * y.astype(x.dtype) - 1.0)
        return jnp.where(inter < -1.0, -4.0 * inter,
                         jnp.where(inter < 1.0, (1.0 - inter) ** 2, 0.0))
    return apply("modified_huber_loss", impl, input, label)


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0, name=None):
    """reference: operators/teacher_student_sigmoid_loss_op.h:34 — CTR
    distillation loss; label encodes (click z, teacher score z'):
    label < -1: z=0 no teacher; label in [-1,0): z=1 no teacher;
    label in [0,1): z=0, z'=label; label >= 1: z=1, z'=label-1."""
    def impl(x, lab):
        x = x.reshape(-1)
        lab = lab.reshape(-1).astype(x.dtype)

        def part(xx, t):
            return jnp.maximum(xx, 0) - xx * t + jnp.log1p(jnp.exp(-jnp.abs(xx)))
        xc = jnp.clip(x, soft_max_lower_bound, soft_max_up_bound)
        z = jnp.where(lab < -1.0, 0.0,
                      jnp.where(lab < 0.0, 1.0, jnp.where(lab < 1.0, 0.0, 1.0)))
        has_teacher = lab >= 0.0
        zprime = jnp.where(lab < 1.0, lab, lab - 1.0)
        loss = part(x, z) + jnp.where(has_teacher, part(xc, zprime), 0.0)
        return loss.reshape(-1, 1)
    return apply("teacher_student_sigmoid_loss", impl, input, label)


def center_loss(input, label, centers, alpha=0.1, update_center=True,
                name=None):
    """reference: operators/center_loss_op.cc — loss = 0.5||x - c_y||^2 per
    sample; returns (loss [N,1], new_centers) where new_centers applies the
    reference's count-normalized update c_y -= alpha * mean(c_y - x_i)."""
    def impl(x, lab, c):
        lab = lab.reshape(-1).astype(jnp.int32)
        diff = x - c[lab]                                # [N, D]
        loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
        if not update_center:
            return loss, c
        n_cls = c.shape[0]
        cnt = jnp.zeros((n_cls,), x.dtype).at[lab].add(1.0)
        upd = jnp.zeros_like(c).at[lab].add(diff)
        new_c = c - alpha * upd / (1.0 + cnt)[:, None]
        return loss, new_c
    return apply("center_loss", impl, input, label, centers)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """reference: operators/margin_rank_loss_op.cc — fluid argument order:
    out = max(0, -label*(left-right) + margin)."""
    def impl(lab, l, r):
        return jnp.maximum(0.0, -lab * (l - r) + margin)
    return apply("margin_rank_loss", impl, label, left, right)


# -- metrics-as-ops -----------------------------------------------------------

def mean_iou(input, label, num_classes, name=None):
    """reference: operators/mean_iou_op.cc — (mean_iou scalar,
    out_wrong [C], out_correct [C])."""
    C = int(num_classes)

    def impl(pred, lab):
        pred = pred.reshape(-1).astype(jnp.int32)
        lab = lab.reshape(-1).astype(jnp.int32)
        correct = jnp.zeros((C,), jnp.int32).at[lab].add(
            (pred == lab).astype(jnp.int32))
        wrong_pred = jnp.zeros((C,), jnp.int32).at[pred].add(
            (pred != lab).astype(jnp.int32))
        wrong_lab = jnp.zeros((C,), jnp.int32).at[lab].add(
            (pred != lab).astype(jnp.int32))
        wrong = wrong_pred + wrong_lab
        denom = correct + wrong
        valid = denom > 0
        iou = jnp.where(valid, correct / jnp.maximum(denom, 1), 0.0)
        miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
        return miou.astype(jnp.float32), wrong, correct
    return apply("mean_iou", impl, input, label)


def precision_recall(max_probs, label, num_classes, weights=None, name=None):
    """reference: operators/precision_recall_op.cc — multiclass
    macro/micro precision, recall, F1. Input is the argmax'd prediction
    (ids) or probability rows; returns batch_metrics [6]:
    [macro-P, macro-R, macro-F1, micro-P, micro-R, micro-F1]."""
    C = int(num_classes)

    def impl(pred, lab):
        if pred.ndim == 2:
            ids = jnp.argmax(pred, axis=1).astype(jnp.int32)
        else:
            ids = pred.reshape(-1).astype(jnp.int32)
        lab = lab.reshape(-1).astype(jnp.int32)
        hit = (ids == lab).astype(jnp.float32)
        tp = jnp.zeros((C,), jnp.float32).at[lab].add(hit)
        fn = jnp.zeros((C,), jnp.float32).at[lab].add(1 - hit)
        fp = jnp.zeros((C,), jnp.float32).at[ids].add(1 - hit)

        def safe(n, d):
            return jnp.where(d > 0, n / jnp.maximum(d, 1e-12), 0.0)
        prec = safe(tp, tp + fp)
        rec = safe(tp, tp + fn)
        f1 = safe(2 * prec * rec, prec + rec)
        present = (tp + fn + fp) > 0
        k = jnp.maximum(jnp.sum(present), 1)
        macro = (jnp.sum(jnp.where(present, prec, 0)) / k,
                 jnp.sum(jnp.where(present, rec, 0)) / k,
                 jnp.sum(jnp.where(present, f1, 0)) / k)
        TP, FP, FN = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
        micro_p = safe(TP, TP + FP)
        micro_r = safe(TP, TP + FN)
        micro_f = safe(2 * micro_p * micro_r, micro_p + micro_r)
        return jnp.stack([macro[0], macro[1], macro[2],
                          micro_p, micro_r, micro_f])
    return apply("precision_recall", impl, max_probs, label)


def positive_negative_pair(score, label, query_id, name=None):
    """reference: operators/positive_negative_pair_op.cc — within each
    query, count ordered pairs: positive (higher-labeled doc scored
    higher), negative (scored lower), neutral (tied score)."""
    def impl(s, lab, q):
        s = s.reshape(-1)
        lab = lab.reshape(-1)
        q = q.reshape(-1)
        same_q = q[:, None] == q[None, :]
        higher = lab[:, None] > lab[None, :]
        valid = same_q & higher
        sd = s[:, None] - s[None, :]
        pos = jnp.sum(valid & (sd > 0))
        neg = jnp.sum(valid & (sd < 0))
        neu = jnp.sum(valid & (sd == 0))
        f = jnp.float32
        return pos.astype(f), neg.astype(f), neu.astype(f)
    return apply("positive_negative_pair", impl, score, label, query_id)


# -- recommender feature ops --------------------------------------------------

def affine_channel(x, scale, bias, data_layout="NCHW", name=None):
    """reference: operators/affine_channel_op.cc — per-channel y = x*s + b."""
    def impl(xx, s, b):
        if data_layout == "NCHW":
            shp = (1, -1) + (1,) * (xx.ndim - 2)
        else:
            shp = (1,) * (xx.ndim - 1) + (-1,)
        return xx * s.reshape(shp) + b.reshape(shp)
    return apply("affine_channel", impl, x, scale, bias)


def data_norm(x, batch_size, batch_sum, batch_square_sum, epsilon=1e-4,
              name=None):
    """reference: operators/data_norm_op.cc:302 — means = sum/size,
    scales = sqrt(size/square_sum); y = (x - mean) * scale. Returns
    (y, means, scales)."""
    def impl(xx, bsz, bsum, bsq):
        means = bsum / bsz
        scales = jnp.sqrt(bsz / bsq)
        return (xx - means[None, :]) * scales[None, :], means, scales
    return apply("data_norm", impl, x, batch_size, batch_sum,
                 batch_square_sum)


def batch_fc(input, w, bias=None, name=None):
    """reference: operators/batch_fc_op.cc — per-slot FC:
    input [S, N, D] x w [S, D, O] (+ bias [S, 1, O]) -> [S, N, O]."""
    def impl(x, ww, *b):
        out = jnp.einsum("snd,sdo->sno", x, ww)
        if b:
            out = out + b[0]
        return out
    args = (input, w) + ((bias,) if bias is not None else ())
    return apply("batch_fc", impl, *args)


def partial_concat(xs, start_index=0, length=-1, name=None):
    """reference: operators/partial_concat_op.cc — concat the column slice
    [start_index, start_index+length) of each 2-D input."""
    def impl(arrs):
        outs = []
        for a in arrs:
            st = start_index if start_index >= 0 else a.shape[1] + start_index
            ln = a.shape[1] - st if length < 0 else length
            outs.append(lax.slice_in_dim(a, st, st + ln, axis=1))
        return jnp.concatenate(outs, axis=1)
    return apply("partial_concat", impl, list(xs))


def partial_sum(xs, start_index=0, length=-1, name=None):
    """reference: operators/partial_sum_op.cc — sum of identical column
    slices across inputs."""
    def impl(arrs):
        outs = []
        for a in arrs:
            st = start_index if start_index >= 0 else a.shape[1] + start_index
            ln = a.shape[1] - st if length < 0 else length
            outs.append(lax.slice_in_dim(a, st, st + ln, axis=1))
        return sum(outs[1:], outs[0])
    return apply("partial_sum", impl, list(xs))


def shuffle_batch(x, seed=0, name=None):
    """reference: operators/shuffle_batch_op.cc — random row permutation;
    returns (shuffled, shuffle_idx) so the caller can unshuffle."""
    key = _gen.next_key() if not seed else jax.random.PRNGKey(int(seed))

    def impl(xx):
        idx = jax.random.permutation(key, xx.shape[0])
        return xx[idx], idx.astype(jnp.int64)
    return apply("shuffle_batch", impl, x)


def cvm(x, use_cvm=True, name=None):
    """reference: operators/cvm_op.cc — CTR show/click feature transform.
    Columns 0/1 are (show, click); use_cvm=True keeps them log-transformed
    (log(show+1), log(click+1)-log(show+1)); False drops them."""
    def impl(xx):
        show = jnp.log(xx[:, :1] + 1.0)
        click = jnp.log(xx[:, 1:2] + 1.0) - show
        if use_cvm:
            return jnp.concatenate([show, click, xx[:, 2:]], axis=1)
        return xx[:, 2:]
    return apply("cvm", impl, x)


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=False,
                     out_val_if_empty=0, name=None):
    """reference: operators/filter_by_instag_op.cc — keep rows whose tag
    set intersects ``filter_tag``. The reference compacts rows (LoD);
    fixed-shape convention here: kept rows stay, dropped rows are
    ``out_val_if_empty``, plus (mask, loss_weight) outputs. Callers that
    need compaction do it host-side."""
    ft = np.asarray(_raw(filter_tag)).reshape(-1)  # noqa: PTA002 -- filter set is a small static list unrolled into the graph

    def impl(x, tags):
        hit = jnp.zeros((tags.shape[0],), jnp.bool_)
        for t in ft.tolist():  # filter set is a small static list
            hit = hit | jnp.any(tags == t, axis=-1)
        m = hit
        shp = (-1,) + (1,) * (x.ndim - 1)
        out = jnp.where(m.reshape(shp), x,
                        jnp.asarray(out_val_if_empty, x.dtype))
        return out, m, m.astype(x.dtype)
    return apply("filter_by_instag", impl, ins, ins_tag)


# -- misc NN ops --------------------------------------------------------------

def row_conv(x, weight, name=None):
    """reference: operators/row_conv_op.cc — lookahead row convolution
    (DeepSpeech2): out_t = sum_{j=0}^{ctx-1} x_{t+j} * w_j (elementwise
    over D). x [B, T, D], weight [ctx, D]."""
    def impl(xx, w):
        ctx = w.shape[0]
        pad = jnp.pad(xx, ((0, 0), (0, ctx - 1), (0, 0)))
        out = jnp.zeros_like(xx)
        for j in range(ctx):  # ctx is small & static; XLA fuses the adds
            out = out + pad[:, j:j + xx.shape[1], :] * w[j][None, None, :]
        return out
    return apply("row_conv", impl, x, weight)


def conv_shift(x, y, name=None):
    """reference: operators/conv_shift_op.cc — circular convolution
    (NTM-style shift): x [B, N], y [B, M] (M odd, M <= N);
    out[b, i] = sum_j x[b, (i + j - M//2) mod N] * y[b, j]."""
    def impl(xx, yy):
        n = xx.shape[1]
        m = yy.shape[1]
        half = m // 2
        idx = (jnp.arange(n)[:, None] + jnp.arange(m)[None, :] - half) % n
        return jnp.einsum("bnm,bm->bn", xx[:, idx], yy)
    return apply("conv_shift", impl, x, y)


def add_position_encoding(x, alpha=1.0, beta=1.0, name=None):
    """reference: operators/add_position_encoding_op.cc — out = alpha*x +
    beta*PE with the interleaved sin/cos transformer encoding
    (PE[pos, i] = sin(pos/10000^(2i/D)) for the first half, cos for the
    second — matching the reference's half-split layout)."""
    def impl(xx):
        b, t, d = xx.shape
        half = d // 2
        pos = jnp.arange(t, dtype=xx.dtype)[:, None]
        div = jnp.power(jnp.asarray(10000.0, xx.dtype),
                        jnp.arange(half, dtype=xx.dtype) / half)
        pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)],
                             axis=1)
        if d % 2:
            pe = jnp.pad(pe, ((0, 0), (0, 1)))
        return alpha * xx + beta * pe[None]
    return apply("add_position_encoding", impl, x)


def correlation(x, y, pad_size, kernel_size, max_displacement, stride1,
                stride2, corr_type_multiply=1, name=None):
    """reference: operators/correlation_op.cc (FlowNet correlation).
    Cost volume between two feature maps: for each displacement (dy, dx)
    in the search window, out channel = mean over C of
    x[..., h, w] * y[..., h+dy, w+dx] (kernel_size=1 form; larger kernels
    average over the patch)."""
    def impl(a, b):
        N, C, H, W = a.shape
        d = max_displacement // stride2
        disp = [(dy * stride2, dx * stride2)
                for dy in range(-d, d + 1) for dx in range(-d, d + 1)]
        bp = jnp.pad(b, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))
        outs = []
        for dy, dx in disp:
            oy, ox = pad_size + dy, pad_size + dx
            shifted = lax.dynamic_slice(bp, (0, 0, oy, ox), (N, C, H, W))
            outs.append(jnp.mean(a * shifted, axis=1))
        out = jnp.stack(outs, axis=1)                    # [N, D*D, H, W]
        if stride1 > 1:
            out = out[:, :, ::stride1, ::stride1]
        return out
    return apply("correlation", impl, x, y)


def similarity_focus(x, axis, indexes, name=None):
    """reference: operators/similarity_focus_op.cc — greedy row/column
    -exclusive argmax mask over X[:, idx] (axis=1), OR-ed across indexes,
    broadcast back to x's shape."""
    if axis != 1:
        raise ValueError("similarity_focus: reference supports axis=1")

    def impl(xx):
        N, C, B, Cc = xx.shape
        K = min(B, Cc)

        def per_index(t):                                # t: [N, B, Cc]
            def body(carry, _):
                t_masked, mask = carry
                flat = t_masked.reshape(N, -1)
                am = jnp.argmax(flat, axis=1)
                i, j = am // Cc, am % Cc
                mask = mask.at[jnp.arange(N), i, j].set(1.0)
                row_used = jnp.zeros((N, B), bool).at[jnp.arange(N), i].set(True)
                col_used = jnp.zeros((N, Cc), bool).at[jnp.arange(N), j].set(True)
                t_masked = jnp.where(row_used[:, :, None] | col_used[:, None, :],
                                     -jnp.inf, t_masked)
                return (t_masked, mask), None
            init = (t, jnp.zeros((N, B, Cc), xx.dtype))
            (_, mask), _ = lax.scan(body, init, None, length=K)
            return mask
        mask = jnp.zeros((N, B, Cc), xx.dtype)
        for idx in indexes:
            mask = jnp.maximum(mask, per_index(xx[:, idx]))
        return jnp.broadcast_to(mask[:, None], xx.shape)
    return apply("similarity_focus", impl, x)


def fsp(x, y, name=None):
    """reference: operators/fsp_op.cc — FSP matrix for distillation:
    out[b, i, j] = (1/(H*W)) sum_hw x[b,i,h,w] * y[b,j,h,w]."""
    def impl(a, b):
        hw = a.shape[2] * a.shape[3]
        return jnp.einsum("bihw,bjhw->bij", a, b) / hw
    return apply("fsp", impl, x, y)


def spp(x, pyramid_height, pool_type="max", name=None):
    """reference: operators/spp_op.cc — spatial pyramid pooling: levels
    l = 0..height-1 pool to a 2^l x 2^l grid, flattened and concatenated
    -> [N, C * sum(4^l)]. The reference computes kernel=ceil(H/bins) with
    zero-padding; here each level is an adaptive pool (identical when the
    bins divide H/W, the usual SPP deployment)."""
    def impl(xx):
        outs = []
        for l in range(int(pyramid_height)):
            p = _adaptive_pool(xx, 2 ** l,
                               "max" if pool_type == "max" else "avg")
            outs.append(p.reshape(xx.shape[0], -1))
        return jnp.concatenate(outs, axis=1)
    return apply("spp", impl, x)


def _adaptive_pool(x, bins, kind):
    n, c, h, w = x.shape
    # integer-boundary adaptive pooling (start/end like the reference's
    # AdaptStartIndex/AdaptEndIndex)
    hs = [(i * h) // bins for i in range(bins)]
    he = [-(-((i + 1) * h) // bins) for i in range(bins)]
    ws = [(j * w) // bins for j in range(bins)]
    we = [-(-((j + 1) * w) // bins) for j in range(bins)]
    rows = []
    for i in range(bins):
        cols = []
        for j in range(bins):
            region = x[:, :, hs[i]:he[i], ws[j]:we[j]]
            cols.append(region.max((2, 3)) if kind == "max"
                        else region.mean((2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)                      # [N, C, bins, bins]


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, name=None):
    """reference: operators/unpool_op.cc — scatter pooled values back to
    the positions recorded by max_pool2d(return_mask=True) (flattened
    per-channel HW index convention)."""
    ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else ((stride, stride)
                                    if isinstance(stride, int)
                                    else tuple(stride))

    def impl(xx, idx):
        n, c, ph, pw = xx.shape
        if output_size is not None:
            oh, ow = output_size
        else:
            oh = (ph - 1) * st[0] + ks[0] - 2 * padding
            ow = (pw - 1) * st[1] + ks[1] - 2 * padding
        flat = jnp.zeros((n, c, oh * ow), xx.dtype)
        out = flat.at[jnp.arange(n)[:, None, None],
                      jnp.arange(c)[None, :, None],
                      idx.reshape(n, c, -1).astype(jnp.int32)].set(
            xx.reshape(n, c, -1))
        return out.reshape(n, c, oh, ow)
    return apply("max_unpool2d", impl, x, indices)


def match_matrix_tensor(x, y, w, x_lengths=None, y_lengths=None, name=None):
    """reference: operators/match_matrix_tensor_op.cc — text-matching
    gram matrix: out[b, t, i, j] = x[b,i,:] . W[:,t,:] . y[b,j,:] over the
    padded+lengths ragged convention (LoD in the reference); padding
    positions are masked to 0."""
    def impl(xx, yy, ww, *lens):
        out = jnp.einsum("bid,dte,bje->btij", xx, ww, yy)
        if lens:
            xl, yl = lens
            mi = jnp.arange(xx.shape[1])[None, :] < xl[:, None]   # [B, Tx]
            mj = jnp.arange(yy.shape[1])[None, :] < yl[:, None]   # [B, Ty]
            out = out * (mi[:, None, :, None] & mj[:, None, None, :])
        return out
    args = (x, y, w) + ((x_lengths, y_lengths)
                        if x_lengths is not None else ())
    return apply("match_matrix_tensor", impl, *args)
