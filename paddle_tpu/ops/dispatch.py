"""Op dispatch: the single funnel every framework op goes through.

TPU-native equivalent of the reference's Tracer::TraceOp + PreparedOp pipeline
(reference: paddle/fluid/imperative/tracer.cc:133, prepared_operator.cc:87):
where the reference looks up a per-(place,dtype) kernel and launches it, here
every op has ONE traceable jnp implementation and dispatch decides:

- eager (dygraph): run it now; if any differentiable input, run under
  ``jax.vjp`` and record a GradNode on the tape (tracer.cc:207).
- static mode: append an op record to the current Program instead of running
  (the reference appends an OpDesc via LayerHelper).
- AMP: an active autocast list may cast float inputs before execution
  (reference: imperative/amp_auto_cast.cc AutoCastInputs).
- FLAGS_check_nan_inf: scan eager outputs for NaN/Inf and abort with the op
  name (reference: framework/details/nan_inf_utils_detail.cc:411).
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.tree_util import tree_flatten, tree_unflatten

from ..core.tensor import Tensor
from ..core import autograd_engine as _ag
from ..core.flags import flag_value
from ..profiler import _ACTIVE as _PROF_ACTIVE

# Live registry: op name -> most recent impl, populated by the dispatch
# funnel itself, so every executed op is introspectable
# (reference: framework/op_info.h:131 OpInfoMap). `registered_ops()` lists
# everything that has run in this process.
OP_REGISTRY = {}


def raw(x):
    """Unwrap a Tensor to its jnp payload (array-likes pass through
    jnp.asarray). The shared helper behind every op module's `_raw`."""
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def registered_ops():
    return sorted(OP_REGISTRY)


# -- eager per-op computation cache ------------------------------------------
# SURVEY §7: "eager-mode performance ... needs aggressive one-op computation
# caching". Key = op name + impl code identity + hashable closure cells +
# non-tensor leaves + tensor signatures + diff positions. jax.vjp closures
# ARE jit-returnable pytrees, so fwd+vjp compiles once per signature
# (~40x less per-call overhead than re-tracing jax.vjp each op call).
from collections import OrderedDict

_EAGER_CACHE = OrderedDict()
_EAGER_CACHE_MAX = 4096  # LRU bound: one entry per op/impl/shape signature
_UNCACHEABLE = object()


def _fn_cache_key(fn):
    """Identity of an op impl: code object + closure cell contents. Returns
    _UNCACHEABLE when a cell holds something we can't key on (arrays, fresh
    RNG keys, Tensors) — those ops take the uncached path."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return _UNCACHEABLE
    cells = getattr(fn, "__closure__", None) or ()
    vals = []
    # per-call values bound via default args (not closure cells) must be in
    # the key too, else two lambdas sharing a code object would collide
    kwdefaults = getattr(fn, "__kwdefaults__", None) or {}
    defaults = tuple(getattr(fn, "__defaults__", None) or ()) + \
        tuple(v for _, v in sorted(kwdefaults.items()))
    for d in defaults:
        if isinstance(d, (int, float, bool, str, bytes, type(None))):
            vals.append(("default", type(d).__name__, d))
        else:
            return _UNCACHEABLE
    for c in cells:
        try:
            v = c.cell_contents
        except ValueError:
            return _UNCACHEABLE
        if isinstance(v, (int, float, bool, str, bytes, type(None))):
            vals.append((type(v).__name__, v))
        elif isinstance(v, (tuple, list)) and all(
                isinstance(e, (int, float, bool, str, type(None))) for e in v):
            vals.append((type(v).__name__, tuple(v)))
        elif isinstance(v, np.dtype) or (isinstance(v, type)
                                         and not issubclass(v, Tensor)):
            vals.append(("dtype", str(v)))
        elif callable(v) and getattr(v, "__closure__", None) is None \
                and hasattr(v, "__qualname__"):
            vals.append(("fn", v.__qualname__))
        else:
            return _UNCACHEABLE
    return (id(code), tuple(vals))


def _leaf_key(leaf):
    if isinstance(leaf, Tensor):
        return ("T", tuple(leaf._data.shape), str(leaf._data.dtype))
    if isinstance(leaf, (int, float, bool, str, bytes, type(None))):
        return ("C", type(leaf).__name__, leaf)
    if isinstance(leaf, (np.ndarray, np.generic)):
        return _UNCACHEABLE
    if isinstance(leaf, (jax.Array,)):
        return _UNCACHEABLE
    try:
        hash(leaf)
        return ("C", type(leaf).__name__, leaf)
    except TypeError:
        return _UNCACHEABLE

# Hook installed by paddle_tpu.static to capture static-mode graph building.
_STATIC_HANDLER = [None]
_STATIC_MODE = [False]

# Hook installed by paddle_tpu.amp for input autocasting: fn(op_name, tensors)->tensors
_AMP_HANDLER = [None]


def enable_static():
    _STATIC_MODE[0] = True


def disable_static():
    _STATIC_MODE[0] = False


def in_dygraph_mode() -> bool:
    return not _STATIC_MODE[0]


def register_static_handler(fn):
    _STATIC_HANDLER[0] = fn


def register_amp_handler(fn):
    _AMP_HANDLER[0] = fn


def _is_tensor_leaf(x):
    return isinstance(x, Tensor)


def apply(name: str, fn: Callable, *args, **attrs):
    """Execute (or record) op ``name`` whose implementation is ``fn``.

    ``args`` may contain Tensors at arbitrary pytree positions (e.g. concat
    takes a list of tensors); ``attrs`` are static python attributes closed
    over at trace time (the reference's OpDesc attrs).
    """
    if _PROF_ACTIVE[0]:
        # per-op host annotation while a profile is being captured
        # (reference: RecordEvent pushed in Tracer::TraceOp, tracer.cc:137)
        with jax.profiler.TraceAnnotation("op::" + name):
            return _apply_inner(name, fn, args, attrs)
    return _apply_inner(name, fn, args, attrs)


def _apply_inner(name, fn, args, attrs):
    leaves, treedef = tree_flatten(args, is_leaf=_is_tensor_leaf)

    if _STATIC_MODE[0] and _STATIC_HANDLER[0] is not None:
        return _STATIC_HANDLER[0](name, fn, args, attrs, leaves, treedef)

    OP_REGISTRY[name] = fn

    t_idx = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    tensors = [leaves[i] for i in t_idx]

    if _AMP_HANDLER[0] is not None and tensors:
        tensors = _AMP_HANDLER[0](name, tensors)
        for i, t in zip(t_idx, tensors):
            leaves[i] = t

    need_grad = (_ag.is_grad_enabled()
                 and any(not t.stop_gradient for t in tensors))

    if need_grad:
        # differentiate w.r.t. only the non-stop-gradient float inputs
        diff_pos = [i for i, t in zip(t_idx, tensors)
                    if not t.stop_gradient and _is_float(t._data.dtype)]
    else:
        diff_pos = []

    # -- cached path: compile fwd(+vjp) once per (impl, signature) ----------
    cache_key = None
    if not any(isinstance(leaves[i]._data, jax.core.Tracer) for i in t_idx):
        fk = _fn_cache_key(fn)
        if fk is not _UNCACHEABLE:
            lks = tuple(_leaf_key(l) for l in leaves)
            if _UNCACHEABLE not in lks:
                try:
                    cache_key = (name, fk, lks, tuple(diff_pos),
                                 tuple(sorted(attrs.items())) if attrs else ())
                    hash(cache_key)
                except TypeError:
                    cache_key = None

    if cache_key is not None:
        entry = _EAGER_CACHE.get(cache_key)
        if entry is None:
            entry = _build_cached(name, fn, leaves, treedef, attrs, t_idx,
                                  diff_pos)
            if len(_EAGER_CACHE) >= _EAGER_CACHE_MAX:
                _EAGER_CACHE.popitem(last=False)
            _EAGER_CACHE[cache_key] = entry
        else:
            _EAGER_CACHE.move_to_end(cache_key)
        jfn, out_td, assemble = entry
        diff_raws = tuple(leaves[p]._data for p in diff_pos)
        other_raws = tuple(leaves[i]._data for i in t_idx
                           if i not in diff_pos)
        if diff_pos:
            out_raw, vjp_fn = jfn(diff_raws, other_raws)
            node = _ag.GradNode(
                name, vjp_fn, [leaves[p] for p in diff_pos],
                [(tuple(o.shape), o.dtype) for o in out_raw],
                replay=(assemble, other_raws))
        else:
            out_raw = jfn(diff_raws, other_raws)
            node = None
        return _wrap_outputs(name, out_raw, node, out_td)

    # -- uncached path (tracers in play / unkeyable impls) ------------------
    out_meta = {}

    def pure(*diff_raws):
        ls = list(leaves)
        for i in t_idx:
            ls[i] = ls[i]._data
        for p, r in zip(diff_pos, diff_raws):
            ls[p] = r
        call_args = tree_unflatten(treedef, ls)
        out = fn(*call_args, **attrs)
        out_leaves, out_td = tree_flatten(out)
        out_meta["td"] = out_td
        return tuple(out_leaves)

    if diff_pos:
        diff_tensors = [leaves[p] for p in diff_pos]
        out_raw, vjp_fn = jax.vjp(pure, *[t._data for t in diff_tensors])
        node = _ag.GradNode(
            name, vjp_fn, diff_tensors,
            [(tuple(o.shape), o.dtype) for o in out_raw],
            replay=(lambda d, _o: pure(*d), ()))
    else:
        out_raw = pure()
        node = None
    return _wrap_outputs(name, out_raw, node, out_meta["td"])


def _build_cached(name, fn, leaves, treedef, attrs, t_idx, diff_pos):
    """Build the jitted fwd(+vjp) for one (impl, signature)."""
    other_pos = [i for i in t_idx if i not in diff_pos]
    const_leaves = [None if isinstance(l, Tensor) else l for l in leaves]
    td_box = {}

    def assemble(diff_raws, other_raws):
        ls = list(const_leaves)
        for p, r in zip(diff_pos, diff_raws):
            ls[p] = r
        for p, r in zip(other_pos, other_raws):
            ls[p] = r
        call_args = tree_unflatten(treedef, ls)
        out = fn(*call_args, **attrs)
        out_leaves, out_td = tree_flatten(out)
        td_box["td"] = out_td
        return tuple(out_leaves)

    if diff_pos:
        def jitted(diff_raws, other_raws):
            return jax.vjp(lambda *d: assemble(d, other_raws), *diff_raws)
    else:
        def jitted(diff_raws, other_raws):
            return assemble(diff_raws, other_raws)
    jfn = jax.jit(jitted)
    # trace once now to capture the output treedef
    jax.eval_shape(jitted,
                   tuple(jax.ShapeDtypeStruct(leaves[p]._data.shape,
                                              leaves[p]._data.dtype)
                         for p in diff_pos),
                   tuple(jax.ShapeDtypeStruct(leaves[p]._data.shape,
                                              leaves[p]._data.dtype)
                         for p in other_pos))
    return jfn, td_box["td"], assemble


def _wrap_outputs(name, out_raw, node, out_td):
    if flag_value("check_nan_inf"):
        _check_nan_inf(name, out_raw)
    out_tensors = []
    for i, o in enumerate(out_raw):
        t = Tensor(o, stop_gradient=(node is None or not _is_float(o.dtype)))
        if node is not None and _is_float(o.dtype):
            t._grad_node = (node, i)
        out_tensors.append(t)
    return tree_unflatten(out_td, out_tensors)


def apply_raw(name: str, fn: Callable, *args, **attrs):
    """Run an op outside autograd entirely (optimizer updates, stats)."""
    with _ag.no_grad():
        return apply(name, fn, *args, **attrs)


def _is_float(dtype) -> bool:
    return (np.issubdtype(np.dtype(dtype), np.inexact)
            or dtype == jnp.bfloat16)


def _check_nan_inf(name, out_raw):
    for o in out_raw:
        if isinstance(o, jax.core.Tracer) or not _is_float(o.dtype):
            continue
        if not bool(jnp.all(jnp.isfinite(o))):
            from ..core.errors import EnforceNotMet
            raise EnforceNotMet(
                f"Operator '{name}' produced NaN/Inf "
                f"(FLAGS_check_nan_inf is on; reference: "
                f"nan_inf_utils_detail.cc:411).")


def register_op(name):
    """Decorator registering a functional op under ``name``."""
    def deco(fn):
        OP_REGISTRY[name] = fn
        return fn
    return deco


def defop(name: str, impl: Callable):
    """Define a standard op: a user-facing function that unwraps Tensors,
    applies ``impl`` and wraps results."""
    OP_REGISTRY[name] = impl

    def op(*args, **kw):
        return apply(name, impl, *args, **kw)
    op.__name__ = name
    return op
