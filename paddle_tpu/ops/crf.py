"""Linear-chain CRF ops (reference: operators/linear_chain_crf_op.h
ForwardOneSequence, crf_decoding_op.h Decode, chunk_eval_op.h).

The reference runs these CPU-only with hand-rolled L1-normalized scaling
to avoid overflow; here the forward recursion is a ``lax.scan`` in
log-space (logsumexp), which is both numerically cleaner and jit/grad-able
— backward comes from autodiff instead of the reference's dedicated
gradient kernel.

Transition layout follows the reference: ``transition`` is [C+2, C] —
row 0 start weights, row 1 stop weights, rows 2.. the square transition
matrix. Ragged batches use the framework's padded+lengths convention.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .dispatch import apply, raw as _raw
from ..core.tensor import Tensor

__all__ = ["linear_chain_crf", "crf_decoding", "viterbi_decode",
           "chunk_eval"]


def linear_chain_crf(emission, transition, label, length=None, name=None):
    """Negative log-likelihood of the labeled path (reference returns
    ``-(score - logZ)``, linear_chain_crf_op.h:240).

    emission [N, T, C]; transition [C+2, C]; label [N, T] int;
    length [N] int (None = full length). Returns [N, 1].
    """
    def impl(em, tr, lab, *maybe_len):
        N, T, C = em.shape
        lab = lab.astype(jnp.int32)
        lens = (maybe_len[0].astype(jnp.int32) if maybe_len
                else jnp.full((N,), T, jnp.int32))
        start, stop, W = tr[0], tr[1], tr[2:]

        # -- logZ by forward recursion ---------------------------------------
        def step(alpha, inp):
            x_t, t = inp                                  # [N, C], scalar t
            nxt = jax.scipy.special.logsumexp(
                alpha[:, :, None] + W[None, :, :], axis=1) + x_t
            alpha = jnp.where((t < lens)[:, None], nxt, alpha)
            return alpha, None
        alpha0 = start[None, :] + em[:, 0]
        ts = jnp.arange(1, T)
        alphaT, _ = lax.scan(step, alpha0,
                             (jnp.moveaxis(em[:, 1:], 1, 0), ts))
        logZ = jax.scipy.special.logsumexp(alphaT + stop[None, :], axis=1)

        # -- labeled path score ----------------------------------------------
        t_idx = jnp.arange(T)
        valid = t_idx[None, :] < lens[:, None]            # [N, T]
        em_sc = jnp.take_along_axis(em, lab[:, :, None], 2)[:, :, 0]
        em_score = jnp.sum(jnp.where(valid, em_sc, 0), axis=1)
        tr_sc = W[lab[:, :-1], lab[:, 1:]]                # [N, T-1]
        tr_valid = t_idx[None, 1:] < lens[:, None]
        tr_score = jnp.sum(jnp.where(tr_valid, tr_sc, 0), axis=1)
        last = jnp.take_along_axis(lab, (lens - 1)[:, None], 1)[:, 0]
        score = (start[lab[:, 0]] + em_score + tr_score + stop[last])
        return (logZ - score)[:, None]
    args = (emission, transition, label) + ((length,)
                                            if length is not None else ())
    return apply("linear_chain_crf", impl, *args)


def crf_decoding(emission, transition, label=None, length=None, name=None):
    """Viterbi decode (reference: crf_decoding_op.h Decode). Returns the
    best tag path [N, T] (positions past ``length`` are 0). If ``label``
    is given, returns per-position correctness instead ([N, T] 0/1),
    matching the reference's eval mode."""
    def impl(em, tr, *rest):
        rest = list(rest)
        lab = rest.pop(0).astype(jnp.int32) if label is not None else None
        lens = (rest.pop(0).astype(jnp.int32) if length is not None
                else jnp.full((em.shape[0],), em.shape[1], jnp.int32))
        N, T, C = em.shape
        start, stop, W = tr[0], tr[1], tr[2:]

        def fwd(carry, inp):
            delta, t = carry, inp[1]
            x_t = inp[0]
            cand = delta[:, :, None] + W[None, :, :]      # [N, C_from, C_to]
            best = jnp.max(cand, axis=1) + x_t
            arg = jnp.argmax(cand, axis=1).astype(jnp.int32)
            nxt = jnp.where((t < lens)[:, None], best, delta)
            arg = jnp.where((t < lens)[:, None], arg,
                            jnp.tile(jnp.arange(C, dtype=jnp.int32)[None, :],
                                     (N, 1)))
            return nxt, arg
        delta0 = start[None, :] + em[:, 0]
        ts = jnp.arange(1, T)
        deltaT, args_rev = lax.scan(fwd, delta0,
                                    (jnp.moveaxis(em[:, 1:], 1, 0), ts))
        lastbest = jnp.argmax(deltaT + stop[None, :], axis=1).astype(jnp.int32)

        # Backtrack: args_rev[k] holds, for each tag at position k+1, its
        # best predecessor at position k. reverse=True walks T-2..0 while
        # emitting tags in position order.
        def rebuild(tag, arg_t):
            prev = jnp.take_along_axis(arg_t, tag[:, None], 1)[:, 0]
            return prev, prev
        _, prevs = lax.scan(rebuild, lastbest, args_rev, reverse=True)
        full = jnp.concatenate([jnp.moveaxis(prevs, 0, 1),
                                lastbest[:, None]], axis=1)
        # mask positions beyond each row's length with 0
        t_idx = jnp.arange(T)[None, :]
        full = jnp.where(t_idx < lens[:, None], full, 0)
        if lab is not None:
            return (full == lab).astype(jnp.int64) * (t_idx < lens[:, None])
        return full.astype(jnp.int64)
    args = (emission, transition)
    if label is not None:
        args = args + (label,)
    if length is not None:
        args = args + (length,)
    return apply("crf_decoding", impl, *args)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """Square-transition Viterbi (the paddle.text ViterbiDecoder API shape;
    that module postdates the reference snapshot, so the BOS/EOS layout is
    defined *here* and pinned by test_extras_crf_ops):

    with ``include_bos_eos_tag=True``, tag ``C-2`` is BOS and tag ``C-1``
    is EOS — row ``C-2`` of ``transition_params`` supplies the BOS->tag
    start scores and column ``C-1`` the tag->EOS stop scores. With False,
    no start/stop scores are added. Returns (scores [N], paths [N, T])."""
    def impl(em, tr, *maybe_len):
        N, T, C = em.shape
        lens = (maybe_len[0].astype(jnp.int32) if maybe_len
                else jnp.full((N,), T, jnp.int32))
        if include_bos_eos_tag:
            start = tr[C - 2]                              # BOS row -> tags
            stop = tr[:, C - 1]                            # tags -> EOS col
        else:
            start = jnp.zeros((C,), em.dtype)
            stop = jnp.zeros((C,), em.dtype)

        def fwd(delta, inp):
            x_t, t = inp
            cand = delta[:, :, None] + tr[None, :, :]
            best = jnp.max(cand, axis=1) + x_t
            arg = jnp.argmax(cand, axis=1).astype(jnp.int32)
            nxt = jnp.where((t < lens)[:, None], best, delta)
            arg = jnp.where((t < lens)[:, None], arg,
                            jnp.tile(jnp.arange(C, dtype=jnp.int32)[None, :],
                                     (N, 1)))
            return nxt, arg
        delta0 = start[None, :] + em[:, 0]
        deltaT, args_rev = lax.scan(
            fwd, delta0, (jnp.moveaxis(em[:, 1:], 1, 0), jnp.arange(1, T)))
        final = deltaT + stop[None, :]
        lastbest = jnp.argmax(final, axis=1).astype(jnp.int32)
        scores = jnp.max(final, axis=1)

        def rebuild(tag, arg_t):
            prev = jnp.take_along_axis(arg_t, tag[:, None], 1)[:, 0]
            return prev, prev
        _, prevs = lax.scan(rebuild, lastbest, args_rev, reverse=True)
        full = jnp.concatenate([jnp.moveaxis(prevs, 0, 1),
                                lastbest[:, None]], axis=1)
        t_idx = jnp.arange(T)[None, :]
        full = jnp.where(t_idx < lens[:, None], full, 0)
        return scores, full.astype(jnp.int64)
    args = (potentials, transition_params) + (
        (lengths,) if lengths is not None else ())
    return apply("viterbi_decode", impl, *args)


# -- chunk_eval (host-side metric, like the reference's CPU-only kernel) ------

def _extract_chunks(tags, scheme, num_chunk_types, excluded=()):
    """Decode (chunk_type, begin, end) spans from a tag sequence under the
    reference's tag layout: tag = chunk_type * num_tag_types + tag_type
    (chunk_eval_op.h GetSegments; lenient conlleval-style parsing — a
    stray continuation tag opens a chunk)."""
    try:
        n_tag = {"plain": 1, "IOB": 2, "IOE": 2, "IOBES": 4}[scheme]
    except KeyError:
        raise ValueError(f"chunk_eval: unknown scheme {scheme!r}")
    chunks = set()
    open_type = None
    start = 0

    def emit(end):
        nonlocal open_type
        if open_type is not None and open_type not in excluded:
            chunks.add((open_type, start, end))
        open_type = None

    for i, t in enumerate(tags):
        t = int(t)
        if 0 <= t < num_chunk_types * n_tag:
            ct, tt = divmod(t, n_tag)
        else:
            ct = tt = None
        if open_type is not None:
            # does position i continue the open chunk?
            cont = ct == open_type and (
                scheme == "plain"
                or (scheme == "IOB" and tt == 1)       # I continues
                or scheme == "IOE"                      # I or E continue
                or (scheme == "IOBES" and tt in (1, 2)))  # I/E continue
            if not cont:
                emit(i - 1)
        if open_type is None and ct is not None:
            open_type, start = ct, i
        # tags that close the chunk at this position
        if open_type is not None and (
                (scheme == "IOE" and tt == 1)           # E
                or (scheme == "IOBES" and tt in (2, 3))):  # E or S
            emit(i)
    emit(len(tags) - 1)
    return chunks


def chunk_eval(input, label, chunk_scheme, num_chunk_types, length=None,
               excluded_chunk_types=None, name=None):
    """reference: operators/chunk_eval_op.h — chunking precision/recall/F1.
    Host-side numpy (it is an eval metric; the reference kernel is
    CPU-only too). Returns (precision, recall, f1, num_infer_chunks,
    num_label_chunks, num_correct_chunks) as python floats/ints."""
    inf = np.asarray(_raw(input))
    lab = np.asarray(_raw(label))
    if inf.ndim == 1:
        inf, lab = inf[None, :], lab[None, :]
    lens = (np.asarray(_raw(length)) if length is not None
            else np.full((inf.shape[0],), inf.shape[1], np.int64))
    excluded = tuple(excluded_chunk_types or ())
    n_inf = n_lab = n_cor = 0
    for row_i, row_l, L in zip(inf, lab, lens):
        ci = _extract_chunks(row_i[:int(L)], chunk_scheme, num_chunk_types,
                             excluded)
        cl = _extract_chunks(row_l[:int(L)], chunk_scheme, num_chunk_types,
                             excluded)
        n_inf += len(ci)
        n_lab += len(cl)
        n_cor += len(ci & cl)
    p = n_cor / n_inf if n_inf else 0.0
    r = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    return p, r, f1, n_inf, n_lab, n_cor
