"""Custom-op extension path: register user ops (jnp compositions, Pallas
TPU kernels, or host C/C++ callbacks) into the framework op surface.

Reference: paddle/fluid/framework/custom_operator.cc:511
RegisterOperatorWithMetaInfo (dynamic registration of ops loaded from user
.so files) + python/paddle/utils/cpp_extension/ (setuptools JIT build).

TPU design (SURVEY §7 decision 3): a custom op is any traceable function —
the dispatch funnel gives it autograd (vjp), AMP visibility, nan-checks
and profiling for free, so "registration" is just binding it into the ops
namespace. Three tiers:
- :func:`register_op` — pure jnp/lax composition (covers ~everything).
- :func:`register_pallas_op` — hand-written Pallas TPU kernel for the rare
  op XLA schedules badly; runs in interpret mode off-TPU so tests stay
  hardware-independent.
- :func:`register_cpp_op` — host-side C/C++ function (built from source
  with the system toolchain, bound via ctypes) wrapped in
  ``jax.pure_callback`` — the ctypes analog of PD_BUILD_OP for host-side
  pre/post-processing.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Callable, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .dispatch import apply, OP_REGISTRY
from ..core.tensor import Tensor


def register_op(name: str, fn: Callable, module=None):
    """Bind ``fn(*raw_arrays, **attrs)`` as op ``name`` on the ops
    namespace: ``paddle.ops.<name>(tensors...)`` with autograd via the
    dispatch funnel (reference: custom_operator.cc RegisterOperator)."""
    import sys
    mod = module or sys.modules["paddle_tpu.ops"]
    if hasattr(mod, name):
        raise ValueError(f"op {name!r} already registered")

    def api(*args, **attrs):
        return apply(name, fn, *args, **attrs)
    api.__name__ = name
    api.__doc__ = fn.__doc__
    setattr(mod, name, api)
    return api


def register_pallas_op(name: str, kernel_call: Callable, module=None):
    """Register an op whose implementation is a pallas_call wrapper.
    ``kernel_call(*raws, interpret=...)`` must accept ``interpret`` so the
    op runs everywhere (interpret=True off-TPU)."""
    def fn(*raws, **attrs):
        on_tpu = jax.devices()[0].platform == "tpu"
        return kernel_call(*raws, interpret=not on_tpu, **attrs)
    fn.__doc__ = kernel_call.__doc__
    return register_op(name, fn, module=module)


def register_cpp_op(name: str, source: str, fn_name: Optional[str] = None,
                    build_dir: Optional[str] = None, module=None):
    """Compile a C/C++ source (exporting
    ``void <fn_name>(const float* in, float* out, long n)`` with C
    linkage) and register it as an elementwise-shaped host op via
    jax.pure_callback (reference: utils/cpp_extension/cpp_extension.py
    setuptools JIT build + PD_BUILD_OP)."""
    fn_name = fn_name or name
    build_dir = build_dir or os.path.join(
        os.path.expanduser("~/.cache/paddle_tpu"), "cpp_ops")
    os.makedirs(build_dir, exist_ok=True)
    src_path = os.path.join(build_dir, f"{name}.cpp")
    so_path = os.path.join(build_dir, f"lib{name}.so")
    with open(src_path, "w") as f:
        f.write(source)
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", "-o", so_path,
                    src_path], check=True, capture_output=True)
    lib = ctypes.CDLL(so_path)
    cfn = getattr(lib, fn_name)
    cfn.argtypes = [ctypes.POINTER(ctypes.c_float),
                    ctypes.POINTER(ctypes.c_float), ctypes.c_long]

    def host(x):
        x = np.ascontiguousarray(x, np.float32)
        out = np.empty_like(x)
        cfn(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), x.size)
        return out

    def fn(a):
        return jax.pure_callback(
            host, jax.ShapeDtypeStruct(a.shape, jnp.float32), a,
            vmap_method="sequential")
    return register_op(name, fn, module=module)


# -- the shipped Pallas kernel: greedy NMS ------------------------------------
#
# Why this op (VERDICT r3 task 10 / profiler finding): greedy NMS is an
# inherently sequential scan over score-sorted candidates; the XLA lowering
# of lax.scan launches one tiny fused loop body per candidate with the
# [k,k] IoU matrix re-read from HBM each step. The Pallas kernel keeps the
# IoU matrix and the kept-mask resident in VMEM across the whole loop —
# one kernel launch, zero HBM traffic in the loop body.

def _nms_kernel(iou_ref, valid_ref, thr_ref, kept_ref, *, unroll=1):
    # Mosaic-friendly formulation: everything 2-D, the kept-mask carried
    # through the fori_loop in vector registers (no per-element VMEM
    # stores), dynamic column selection via a masked reduction.
    k = iou_ref.shape[0]
    iou = iou_ref[:]                                          # [k, k]
    vvec = valid_ref[:]                                       # [k, 1]
    thr = thr_ref[0, 0]
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (k, 1), 0)
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (1, k), 1)

    def body(i, kept):                                        # kept [k, 1]
        row = jnp.sum(iou * (col_ids == i).astype(iou.dtype),
                      axis=1, keepdims=True)                  # iou[:, i]
        sup = jnp.any((kept == 1) & (row > thr) & (row_ids < i))
        valid_i = jnp.any((row_ids == i) & (vvec != 0))
        keep_i = jnp.logical_and(valid_i, jnp.logical_not(sup))
        return jnp.where(row_ids == i, keep_i.astype(jnp.int32), kept)

    kept_ref[:] = jax.lax.fori_loop(0, k, body,
                                    jnp.zeros((k, 1), jnp.int32),
                                    unroll=unroll)


def _nms_unroll(k: int) -> int:
    """Loop-unroll factor from the autotuner's winner cache (key
    ``nms|{platform}|k{k}``); 1 — the historical behavior — when no
    winner is known. The sequential scan's body is tiny, so unrolling
    amortizes per-iteration scalar overhead."""
    try:
        from ..tuner import get_nms_config
        cfg = get_nms_config(k)
        u = int(cfg["unroll"]) if cfg else 1
    except Exception:
        return 1
    # a bad factor would change trip arithmetic; only accept exact
    # divisors of the candidate count
    return u if u >= 1 and k % u == 0 else 1


def pallas_greedy_nms(iou, valid, thr, interpret=False, unroll=None):
    """Greedy NMS over score-sorted candidates as ONE Pallas kernel.

    iou [k,k] f32 (symmetric, sorted by score desc), valid [k] int32,
    thr [1] f32 → kept mask [k] int32. Matches the lax.scan reference in
    detection._greedy_nms_mask (equivalence-tested); the IoU matrix and
    the mask stay VMEM/register resident across the whole loop.
    ``unroll=None`` defers the loop-unroll factor to the tuner cache.
    """
    import functools

    from jax.experimental import pallas as pl

    k = iou.shape[0]
    if unroll is None:
        unroll = _nms_unroll(k)
    out = pl.pallas_call(
        functools.partial(_nms_kernel, unroll=int(unroll)),
        out_shape=jax.ShapeDtypeStruct((k, 1), jnp.int32),
        interpret=interpret,
    )(iou.astype(jnp.float32), valid.reshape(k, 1).astype(jnp.int32),
      thr.reshape(1, 1).astype(jnp.float32))
    return out.reshape(k)
