"""Linear-algebra ops (paddle.linalg parity).

Parity targets: cholesky, inverse, matrix_power, matrix_rank, svd, qr, eig,
eigh, eigvals, det, slogdet, solve, triangular_solve, lstsq, pinv, lu, cond,
multi_dot (reference: paddle/fluid/operators/cholesky_op.cc, inverse_op.cc,
svd_op.cc-era additions). On TPU these lower to XLA's linalg custom calls.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import scipy as jsp

from .dispatch import apply


def cholesky(x, upper=False, name=None):
    def impl(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2).conj() if upper else l
    return apply("cholesky", impl, x)


def inv(x, name=None):
    return apply("inverse", jnp.linalg.inv, x)


inverse = inv


def matrix_power(x, n, name=None):
    return apply("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply("matrix_rank", lambda a: jnp.linalg.matrix_rank(a, tol=tol), x)


def det(x, name=None):
    return apply("determinant", jnp.linalg.det, x)


def slogdet(x, name=None):
    def impl(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])
    return apply("slogdeterminant", impl, x)


def svd(x, full_matrices=False, name=None):
    return apply("svd", lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x)


def qr(x, mode="reduced", name=None):
    return apply("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x)


def eig(x, name=None):
    return apply("eig", lambda a: tuple(jnp.linalg.eig(a)), x)


def eigh(x, UPLO="L", name=None):
    return apply("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x)


def eigvals(x, name=None):
    return apply("eigvals", jnp.linalg.eigvals, x)


def eigvalsh(x, UPLO="L", name=None):
    return apply("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x)


def solve(x, y, name=None):
    return apply("solve", jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def impl(a, b):
        return jsp.linalg.solve_triangular(a, b, lower=not upper,
                                           trans=1 if transpose else 0,
                                           unit_diagonal=unitriangular)
    return apply("triangular_solve", impl, x, y)


def cholesky_solve(x, y, upper=False, name=None):
    def impl(b, l):
        return jsp.linalg.cho_solve((l, not upper), b)
    return apply("cholesky_solve", impl, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def impl(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return (sol, res, rank, sv)
    return apply("lstsq", impl, x, y)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), x)


def lu(x, pivot=True, get_infos=False, name=None):
    def impl(a):
        lu_mat, piv = jsp.linalg.lu_factor(a)
        return (lu_mat, piv.astype(jnp.int32))
    return apply("lu", impl, x)


def cond(x, p=None, name=None):
    return apply("cond_linalg", lambda a: jnp.linalg.cond(a, p=p), x)


def multi_dot(x, name=None):
    return apply("multi_dot", lambda xs: jnp.linalg.multi_dot(xs), list(x))


def matrix_exp(x, name=None):
    return apply("matrix_exp", jsp.linalg.expm, x)


def householder_product(x, tau, name=None):
    def impl(a, t):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(n):
            v = jnp.concatenate([jnp.zeros(i, a.dtype), jnp.ones(1, a.dtype), a[i + 1:, i]])
            q = q - t[i] * (q @ jnp.outer(v, v))
        return q[:, :n]
    return apply("householder_product", impl, x, tau)
