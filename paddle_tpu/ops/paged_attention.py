"""Paged decode attention as a Pallas TPU kernel (vLLM/PagedAttention).

One query per sequence attends over K/V rows scattered across a paged
arena: logical row ``t`` of sequence ``s`` lives at physical page
``block_tables[s, t // page_size]``, in-page offset ``t % page_size``
(see ``serving/llm/paged/pool.py``). Rather than gathering the pages
into a contiguous ``[S, max_seq, H, D]`` tensor in HBM first (the
reference lane, ``paged_gather_rows``), this kernel walks the block
table *inside* the grid: the page id rides the scalar-prefetch channel
into each K/V BlockSpec index map, so the pipeline DMAs exactly the
pages the sequence owns, one per grid step, with the online-softmax
running statistics (m, l, acc) carried across the page axis in VMEM
scratch — the flash-attention recurrence over a gathered key axis.

Grid: ``(S, H // block_h, pages_per_seq)`` — the page axis is innermost,
so on TPU (sequential grid) the scratch accumulators persist across one
sequence-head-block's page walk and reset via ``@pl.when(p == 0)``.

Masking: query at position ``positions[s]`` attends rows ``j <=
positions[s]`` (the just-written token sees itself and the whole valid
prefix — same semantics as ``kvcache.valid_mask``). Pages past the
length (including trash-page junk) zero out in the running softmax.

Off-TPU the wrapper runs in interpret mode — the same numerics, so CPU
tests cover the kernel's math; interpret-mode output matches the gather
lane to float tolerance (NOT bitwise: the blocked online-softmax sums in
a different order — the bitwise-parity contract belongs to the gather
lane).

Tuner family ``paged_attn`` (``paddle_tpu.tuner.paged_key``): the one
knob is ``block_h``, how many heads share a grid step's DMA and compute
block. ``default_winners.json`` carries committed entries; unknown
shapes fall back to a dividing heuristic.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["paged_attention"]

_NEG_INF = -1e30


def _sanitize_block_h(block_h, num_heads: int) -> int:
    """Largest divisor of ``num_heads`` that is <= the requested block
    (the grid needs H % block_h == 0)."""
    b = max(1, min(int(block_h), num_heads))  # noqa: PTA001 -- block_h is a python config int (tuner winner / heuristic), never a traced value
    while num_heads % b:
        b -= 1
    return b


def _tuned_block_h(num_heads, head_dim, page_size, dtype):
    """The ``paged_attn`` family's tuned block_h, or None when untuned
    (tuner import kept lazy + failure-proof, like the flash families)."""
    try:
        from ..tuner import get_paged_attn_config
        cfg = get_paged_attn_config(num_heads, head_dim, page_size, dtype)
    except Exception:
        return None
    if not cfg:
        return None
    try:
        b = int(cfg.get("block_h", 0))
    except (TypeError, ValueError):
        return None
    return b if b > 0 else None


def _paged_attn_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                       acc_ref, m_ref, l_ref, *, scale, page_size,
                       pages_per_seq, block_h):
    import jax.experimental.pallas as pl

    s = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32) * scale          # [bh, D]
    kt = jnp.transpose(k_ref[0].astype(jnp.float32),
                       (1, 0, 2))                     # [bh, page, D]
    vt = jnp.transpose(v_ref[0].astype(jnp.float32),
                       (1, 0, 2))
    # scores: head-batched q·k over the page rows -> [bh, page]
    s_blk = lax.dot_general(q[:, None, :], kt,
                            (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)[:, 0, :]
    j = p * page_size + lax.broadcasted_iota(jnp.int32,
                                             (block_h, page_size), 1)
    valid = j <= len_ref[s]
    s_blk = jnp.where(valid, s_blk, _NEG_INF)
    m_prev = m_ref[:, 0]                              # [bh]
    l_prev = l_ref[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s_blk, axis=1))
    alpha = jnp.exp(m_prev - m_new)
    pexp = jnp.where(valid, jnp.exp(s_blk - m_new[:, None]), 0.0)
    l_new = l_prev * alpha + jnp.sum(pexp, axis=1)
    acc_ref[...] = (acc_ref[...] * alpha[:, None]
                    + lax.dot_general(pexp, vt,
                                      (((1,), (1,)), ((0,), (0,))),
                                      preferred_element_type=jnp.float32))
    m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...]
                    / jnp.maximum(l_ref[:, 0], 1e-30)[:, None]
                    ).astype(o_ref.dtype)


def paged_attention(q, k_arena, v_arena, block_tables, positions,
                    scale=None, block_h=None, interpret=None):
    """Single-token decode attention through a paged KV arena.

    ``q``: ``[S, H, D]`` (one query per sequence, already projected);
    ``k_arena``/``v_arena``: ``[num_pages + 1, page_size, H, D]``
    single-layer arena views (dense — int8 arenas take the gather lane,
    which dequantizes in-graph); ``block_tables``: ``[S, pages_per_seq]``
    int32; ``positions``: ``[S]`` int32 — query ``s`` attends logical
    rows ``j <= positions[s]``. Returns ``[S, H, D]`` in ``q.dtype``.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if isinstance(k_arena, dict) or isinstance(v_arena, dict):
        raise ValueError(
            "paged_attention kernel reads dense arenas only — the int8 "
            "lane uses the gather implementation (dequantize in-graph)")
    s_n, num_heads, head_dim = q.shape
    page_size = k_arena.shape[1]
    pages_per_seq = block_tables.shape[1]
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if scale is None:
        scale = 1.0 / np.sqrt(head_dim)
    if block_h is None:
        block_h = _tuned_block_h(num_heads, head_dim, page_size, q.dtype)
    if block_h is None:
        # heuristic: 8 heads per step keeps the f32 sublane tile full on
        # TPU; off-TPU any divisor is fine
        block_h = 8 if not interpret else num_heads
    block_h = _sanitize_block_h(block_h, num_heads)

    kernel = functools.partial(
        _paged_attn_kernel, scale=scale, page_size=page_size,
        pages_per_seq=pages_per_seq, block_h=block_h)
    bt_flat = block_tables.reshape(-1).astype(jnp.int32)

    def _q_map(s, h, p, bt_ref, len_ref):
        return (s, h, 0)

    def _kv_map(s, h, p, bt_ref, len_ref):
        # the block-table walk: physical page id -> arena block index
        return (bt_ref[s * pages_per_seq + p], 0, h, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_n, num_heads // block_h, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, block_h, head_dim), _q_map),
            pl.BlockSpec((1, page_size, block_h, head_dim), _kv_map),
            pl.BlockSpec((1, page_size, block_h, head_dim), _kv_map),
        ],
        out_specs=pl.BlockSpec((1, block_h, head_dim), _q_map),
        scratch_shapes=[
            pltpu.VMEM((block_h, head_dim), jnp.float32),   # acc
            pltpu.VMEM((block_h, 128), jnp.float32),        # running max
            pltpu.VMEM((block_h, 128), jnp.float32),        # running sum
        ])
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_n, num_heads, head_dim),
                                       q.dtype),
        interpret=interpret,
    )(bt_flat, positions.astype(jnp.int32), q, k_arena, v_arena)
