"""Flash attention as a Pallas TPU kernel.

The reference computes attention as unfused matmul/softmax/matmul over
materialized [B, H, S, S] score tensors (multihead_matmul fusion only at
inference). The TPU-native hot path keeps scores block-resident in VMEM
with the online-softmax recurrence (Dao et al.) — O(S) memory instead of
O(S²) HBM traffic, which is what makes long-sequence training fit at all
(the ring-attention sequence parallelism in fleet/sequence_parallel.py
shards S *across* chips; this kernel is the per-chip inner loop story).

Kernel shape: grid (B*H, S_q/block_q); each program holds one q block and
its running (acc, m, l) statistics in VMEM/registers while scanning k/v
blocks with ``lax.fori_loop``. Causal masking and tail padding are mask
arithmetic inside the score block — shapes stay static.

Runs in interpret mode off-TPU so tests are hardware-independent
(ops/custom.py register_pallas_op convention).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .dispatch import apply

__all__ = ["flash_attention"]

_NEG_INF = -1e30

#: historical hand-picked block edge — the fallback when the autotuner
#: has no winner for a shape (paddle_tpu.tuner consults disk winners and
#: the committed defaults table first)
DEFAULT_BLOCK = 128


def _ceil16(n: int) -> int:
    return max(16, -(-int(n) // 16) * 16)


def _sanitize_block(block: int, length: int) -> int:
    """Clamp a requested block edge to a legal Mosaic tile: a multiple of
    16 rows (the sublane tile for both f32 and bf16), at most the
    16-rounded sequence length. Tuner- or user-supplied blocks that
    violate the constraint are rounded up rather than rejected — the
    caller's padding absorbs the difference."""
    b = int(block)
    if b <= 0:
        b = DEFAULT_BLOCK
    b = _ceil16(b)
    return min(b, _ceil16(length))


def _tuned_blocks(q_len, kv_len, head_dim, dtype, causal):
    """(block_q, block_k) from the autotuner's winner cache, or None.
    Never raises: an unavailable/broken tuner degrades to the default."""
    try:
        from ..tuner import get_flash_blocks
        return get_flash_blocks(q_len, kv_len, head_dim, dtype, causal)
    except Exception:
        return None


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, scale, causal,
               block_q, block_k, seq_len, kv_len):
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale                 # [bq, D]
    d = q.shape[-1]
    q_idx = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    n_k = kv_len // block_k

    def body(j, carry):
        acc, m, l = carry
        kblk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_idx = j * block_k + lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 1)
        mask = k_idx < seq_len                               # tail padding
        if causal:
            mask = mask & (q_idx >= k_idx)
        s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    if causal:
        # early exit: k blocks entirely above the diagonal contribute
        # nothing — trip count becomes data-independent-per-program
        # ceil(((qi+1)*block_q) / block_k), halving work on average
        n_k = jnp.minimum(n_k, (qi * block_q + block_q + block_k - 1)
                          // block_k)
    acc, m, l = lax.fori_loop(0, n_k, body, (acc0, m0, l0))
    # fully-masked rows (padding queries) have l == 0
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0] = out.astype(o_ref.dtype)
    if lse_ref is not None:
        # logsumexp of the score rows: backward recomputes P from it
        # (shape [1, 1, bq]: TPU block rule needs the last two dims
        # (sublane, lane)-aligned, so the row stats ride a lane axis)
        lse_ref[0, 0] = (m + jnp.log(jnp.maximum(l, 1e-30))).astype(jnp.float32)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, *, scale=None, block_q=None,
                    block_k=None, name=None):
    """Memory-efficient exact attention (paddle's flash_attention API:
    same positional order ``(q, k, v, dropout, causal, return_softmax)``
    and the same ``(out, softmax)`` tuple return, so positionally-ported
    reference code keeps its meaning).

    query/key/value: [batch, seq, num_heads, head_dim]. Returns
    ``(out [batch, seq, num_heads, head_dim], None)`` — the attention
    probabilities are never materialized (that is the point of the
    kernel), so ``return_softmax=True`` raises, as does ``dropout > 0``
    (attention-prob dropout needs the dense path).

    ``block_q``/``block_k`` default to the autotuner's winner for the
    (shape, dtype, platform) key — falling back to the historical 128
    when no winner is cached. Explicit values win over the tuner.

    The sequence is padded to the block size internally; padded keys are
    masked, padded query rows are sliced away.
    """
    if dropout:
        raise ValueError("flash_attention: dropout inside the fused kernel "
                         "is unsupported (use the dense path for "
                         "attention-prob dropout)")
    if return_softmax:
        raise ValueError("flash_attention: the probability matrix is never "
                         "materialized; return_softmax is unsupported")

    def impl(q, kk, vv):
        b, s, h, d = q.shape
        skv = kk.shape[1]
        sc = scale if scale is not None else 1.0 / np.sqrt(d)
        bq_req, bk_req = block_q, block_k
        if bq_req is None and bk_req is None:
            tuned = _tuned_blocks(s, skv, d, q.dtype, causal)
            if tuned is not None:
                bq_req, bk_req = tuned
        if bq_req is None:
            bq_req = DEFAULT_BLOCK
        if bk_req is None:
            bk_req = DEFAULT_BLOCK
        # block shapes must stay multiples of the sublane tile (8 rows for
        # f32, 16 for bf16) or Mosaic may fail to compile (odd seq lengths
        # like 100); round to 16 so both dtypes are safe — the seq is
        # padded up to the rounded block below, padded keys masked
        bq = _sanitize_block(bq_req, s)
        bk = _sanitize_block(bk_req, skv)
        s_pad = -(-s // bq) * bq
        kv_pad = -(-skv // bk) * bk

        def to_bh(x, pad_to):
            x = jnp.moveaxis(x, 2, 1).reshape(b * h, x.shape[1], d)
            if pad_to != x.shape[1]:
                x = jnp.pad(x, ((0, 0), (0, pad_to - x.shape[1]), (0, 0)))
            return x
        qb = to_bh(q, s_pad)
        kb = to_bh(kk, kv_pad)
        vb = to_bh(vv, kv_pad)
        on_tpu = jax.devices()[0].platform == "tpu"
        # real kv length for the padding mask: padded keys sit at
        # index >= skv
        out = _fa_core(qb, kb, vb, causal, sc, bq, bk, not on_tpu, skv)
        out = out[:, :s, :].reshape(b, h, s, d)
        return jnp.moveaxis(out, 1, 2)
    return apply("flash_attention", impl, query, key, value), None


# -- backward kernels (FlashAttention-style recomputation) --------------------

def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, *, scale, causal, block_q, block_k, seq_len,
                      kv_len):
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)                        # [bq, D]
    lse = lse_ref[0, 0].astype(jnp.float32)                   # [bq]
    delta = delta_ref[0, 0].astype(jnp.float32)               # [bq]
    q_idx = qi * block_q + lax.broadcasted_iota(jnp.int32,
                                                (block_q, block_k), 0)
    n_k = kv_len // block_k
    if causal:
        n_k = jnp.minimum(n_k, (qi * block_q + block_q + block_k - 1)
                          // block_k)

    def body(j, dq):
        kblk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        k_idx = j * block_k + lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 1)
        mask = k_idx < seq_len
        if causal:
            mask = mask & (q_idx >= k_idx)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)   # [bq, bk]
        dp = lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq + lax.dot_general(ds, kblk, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
    dq = lax.fori_loop(0, n_k,
                       body, jnp.zeros((block_q, q.shape[-1]), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                       seq_len, q_len):
    import jax.experimental.pallas as pl

    ki = pl.program_id(1)
    kblk = k_ref[0].astype(jnp.float32)                       # [bk, D]
    vblk = v_ref[0].astype(jnp.float32)
    k_idx = ki * block_k + lax.broadcasted_iota(jnp.int32,
                                                (block_q, block_k), 1)
    n_q = q_len // block_q

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32) \
            * scale
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)].astype(jnp.float32)
        delta = delta_ref[0, 0,
                          pl.ds(i * block_q, block_q)].astype(jnp.float32)
        s = lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        q_idx = i * block_q + lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 0)
        mask = k_idx < seq_len
        if causal:
            mask = mask & (q_idx >= k_idx)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dv2 = dv + lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk2 = dk + lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        return dk2, dv2
    if causal:
        # q blocks entirely above this k block see it masked; start there
        i0 = (ki * block_k) // block_q
    else:
        i0 = 0
    zero = jnp.zeros((block_k, kblk.shape[-1]), jnp.float32)
    dk, dv = lax.fori_loop(i0, n_q, body, (zero, zero))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _fa_fwd_with_lse(qb, kb, vb, causal, sc, bq, bk, interpret, true_kv):
    """Forward kernel call; also emits the [bh, 1, S] f32 logsumexp rows
    (1/(2·D) of the output bytes — cheap enough to pay on inference
    too, so there is a single forward kernel to maintain)."""
    import jax.experimental.pallas as pl

    bh, s_pad, d = qb.shape
    kv_pad = kb.shape[1]
    # the grid floor-divides: a non-dividing block would silently drop the
    # tail rows/keys for direct callers (flash_attention() pads before
    # calling, but ring-flash and the tuner call this core directly)
    if s_pad % bq or kv_pad % bk:
        raise ValueError(
            f"flash attention core: block_q={bq} / block_k={bk} must "
            f"divide the (padded) sequence lengths ({s_pad}, {kv_pad}); "
            "pad the operands or pick a dividing block")
    kernel = functools.partial(
        _fa_kernel, scale=sc, causal=causal, block_q=bq, block_k=bk,
        seq_len=true_kv, kv_len=kv_pad)
    return pl.pallas_call(
        kernel,
        grid=(bh, s_pad // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, kv_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, kv_pad, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i))],
        out_shape=[jax.ShapeDtypeStruct((bh, s_pad, d), qb.dtype),
                   jax.ShapeDtypeStruct((bh, 1, s_pad), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb)


def _tuned_bwd_blocks(s_pad, kv_pad, head_dim, dtype, causal, bq, bk):
    """(block_q, block_k) for the backward kernels: the tuner's
    ``flash_bwd`` winner when one exists AND divides the padded grid
    (the backward pallas_calls floor-divide exactly like the forward),
    else the forward blocks the residuals were produced with."""
    try:
        from ..tuner import get_flash_blocks
        tuned = get_flash_blocks(s_pad, kv_pad, head_dim, dtype, causal,
                                 bwd=True)
    except Exception:
        tuned = None
    if tuned is not None:
        tbq, tbk = int(tuned[0]), int(tuned[1])
        if (tbq > 0 and tbk > 0 and s_pad % tbq == 0
                and kv_pad % tbk == 0 and tbq % 16 == 0 and tbk % 16 == 0):
            return tbq, tbk
    return bq, bk


def _fa_bwd_with_lse(qb, kb, vb, do, out, lse, causal, sc, bq, bk,
                     interpret, true_kv, delta=None, grad_dtypes=None):
    """Backward kernel calls (FlashAttention recomputation schedule):
    given the saved residuals — ``out`` and the ``[bh, 1, S]`` f32
    logsumexp rows from :func:`_fa_fwd_with_lse` — recompute P block-wise
    as ``exp(s·scale − lse)`` and emit (dQ, dK, dV) with f32 accumulators.

    ``delta`` is the rowsum(dO∘O) softmax-jacobian correction
    ``[bh, 1, S]``; computed here from ``out`` when not supplied (ring
    callers precompute it once per rank because it is chunk-independent,
    and pass ``out=None``). ``grad_dtypes`` overrides the emitted grad
    dtypes (default: the operand dtypes) — the ring backward requests f32
    so per-chunk grads accumulate without intermediate rounding."""
    import jax.experimental.pallas as pl

    bh, s_pad, d = qb.shape
    kv_pad = kb.shape[1]
    if s_pad % bq or kv_pad % bk:
        raise ValueError(
            f"flash attention backward: block_q={bq} / block_k={bk} must "
            f"divide the (padded) sequence lengths ({s_pad}, {kv_pad})")
    if delta is None:
        # delta = rowsum(dO * O) — the softmax-jacobian correction term
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1)[:, None, :]                  # [bh, 1, s_pad]
    dq_dt, dk_dt, dv_dt = grad_dtypes or (qb.dtype, kb.dtype, vb.dtype)

    dq_kernel = functools.partial(
        _fa_bwd_dq_kernel, scale=sc, causal=causal, block_q=bq, block_k=bk,
        seq_len=true_kv, kv_len=kv_pad)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, s_pad // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, kv_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, kv_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s_pad, d), dq_dt),
        interpret=interpret,
    )(qb, kb, vb, do, lse, delta)

    dkv_kernel = functools.partial(
        _fa_bwd_dkv_kernel, scale=sc, causal=causal, block_q=bq,
        block_k=bk, seq_len=true_kv, q_len=s_pad)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, kv_pad // bk),
        in_specs=[
            pl.BlockSpec((1, s_pad, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, s_pad, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 1, s_pad), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 1, s_pad), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
                   pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0))],
        out_shape=[jax.ShapeDtypeStruct((bh, kv_pad, d), dk_dt),
                   jax.ShapeDtypeStruct((bh, kv_pad, d), dv_dt)],
        interpret=interpret,
    )(qb, kb, vb, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _fa_core(qb, kb, vb, causal, sc, bq, bk, interpret, true_kv):
    out, _ = _fa_fwd_with_lse(qb, kb, vb, causal, sc, bq, bk, interpret,
                              true_kv)
    return out


def _fa_core_fwd(qb, kb, vb, causal, sc, bq, bk, interpret, true_kv):
    out, lse = _fa_fwd_with_lse(qb, kb, vb, causal, sc, bq, bk, interpret,
                                true_kv)
    return out, (qb, kb, vb, out, lse)


def _fa_core_bwd(causal, sc, bq, bk, interpret, true_kv, res, do):
    qb, kb, vb, out, lse = res
    bh, s_pad, d = qb.shape
    # backward blocks may differ from the forward's (the lse/delta rows
    # are full-length arrays; only grid divisibility ties them together)
    bbq, bbk = _tuned_bwd_blocks(s_pad, kb.shape[1], d, qb.dtype, causal,
                                 bq, bk)
    return _fa_bwd_with_lse(qb, kb, vb, do, out, lse, causal, sc, bbq,
                            bbk, interpret, true_kv)


_fa_core.defvjp(_fa_core_fwd, _fa_core_bwd)
