"""Sequence ops over the masked-ragged convention.

The reference represents ragged batches as LoDTensor — a flat value tensor
plus level-of-detail offsets (reference: paddle/fluid/framework/
lod_tensor.h:109) consumed by the ~30 ops in
paddle/fluid/operators/sequence_ops/. A static-shape compiler can't carry
data-dependent offsets, so this framework's ragged convention is
**padded + lengths** (SURVEY "hard parts" #1):

    data:    [B, T, ...]  — batch of sequences padded to T
    lengths: [B] int      — true length of each row

Every op here takes/returns that pair (lengths may be None = fully dense).
This is the same trade the reference itself makes at inference (its
sequence_pad/unpad ops convert LoD <-> padded, sequence_pad_op.cc);
here padded IS the native form and lod exists only at the API edge.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .dispatch import apply, raw as _raw
from ..core.tensor import Tensor


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """reference: operators/sequence_ops/sequence_mask_op.cc — lengths [B]
    -> mask [B, maxlen]."""
    d = np.dtype(dtype) if dtype != "bool" else np.bool_
    ml = int(maxlen) if maxlen is not None else int(np.asarray(_raw(x)).max())

    def impl(lens):
        r = jnp.arange(ml)
        return (r[None, :] < lens[..., None]).astype(d)
    return apply("sequence_mask", impl, x)


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """reference: sequence_pad_op.cc. Input here is (flat values [N, ...],
    lengths [B]) — the LoD edge form; returns ([B, T, ...], lengths).
    ``maxlen`` must be static (None = max length rounded up at trace time
    from the concrete lengths)."""
    lens_np = np.asarray(_raw(length))
    B = int(lens_np.shape[0])
    T = int(maxlen) if maxlen is not None else int(lens_np.max())
    offs = np.concatenate([[0], np.cumsum(lens_np)]).astype(np.int32)

    def impl(flat, pv, lens):
        idx = offs[:-1, None] + np.arange(T)[None, :]
        idx = jnp.minimum(jnp.asarray(idx), flat.shape[0] - 1)
        rows = flat[idx]                      # [B, T, ...]
        mask = jnp.arange(T)[None, :] < lens[:, None]
        mshape = mask.shape + (1,) * (rows.ndim - 2)
        return jnp.where(mask.reshape(mshape), rows,
                         jnp.asarray(pv, rows.dtype))
    out = apply("sequence_pad", impl, x, pad_value, length)
    return out, length


def sequence_unpad(x, length, name=None):
    """reference: sequence_unpad_op.cc — padded [B, T, ...] + lengths ->
    flat [N, ...] (N = sum(lengths), computed at trace time from the
    concrete lengths — the one unavoidable host sync of the ragged edge)."""
    lens_np = np.asarray(_raw(length))
    T = int(_raw(x).shape[1])
    keep = np.concatenate([np.arange(l) + i * T
                           for i, l in enumerate(lens_np)]).astype(np.int32)

    def impl(padded, lens):
        flat = padded.reshape((-1,) + padded.shape[2:])
        return flat[jnp.asarray(keep)]
    return apply("sequence_unpad", impl, x, length)


def sequence_pool(x, pool_type="sum", lengths=None, pad_value=0.0, name=None):
    """reference: sequence_pool_op.cc (sum/average/sqrt/max/min/last/first
    over each row's valid prefix)."""
    pt = pool_type.lower()

    def impl(data, *rest):
        lens = rest[0] if rest else None
        T = data.shape[1]
        if lens is None:
            mask = jnp.ones(data.shape[:2], bool)
            lensf = jnp.full((data.shape[0],), T, jnp.float32)
        else:
            mask = jnp.arange(T)[None, :] < lens[:, None]
            lensf = jnp.maximum(lens.astype(jnp.float32), 1.0)
        mshape = mask.shape + (1,) * (data.ndim - 2)
        m = mask.reshape(mshape)
        if pt == "sum":
            return jnp.sum(jnp.where(m, data, 0), axis=1)
        if pt == "average":
            s = jnp.sum(jnp.where(m, data, 0), axis=1)
            return s / lensf.reshape((-1,) + (1,) * (data.ndim - 2))
        if pt == "sqrt":
            s = jnp.sum(jnp.where(m, data, 0), axis=1)
            return s / jnp.sqrt(lensf).reshape((-1,) + (1,) * (data.ndim - 2))
        if pt == "max":
            return jnp.max(jnp.where(m, data, -jnp.inf), axis=1)
        if pt == "min":
            return jnp.min(jnp.where(m, data, jnp.inf), axis=1)
        if pt == "first":
            return data[:, 0]
        if pt == "last":
            if lens is None:
                return data[:, -1]
            i = jnp.maximum(lens - 1, 0)
            return jnp.take_along_axis(
                data, i.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1
            ).squeeze(1)
        raise ValueError(f"bad pool_type {pool_type}")
    args = (x,) + ((lengths,) if lengths is not None else ())
    return apply("sequence_pool", impl, *args)


def sequence_first_step(x, lengths=None):
    """reference: sequence_ops — first-step pooling."""
    return sequence_pool(x, "first", lengths)


def sequence_last_step(x, lengths=None):
    return sequence_pool(x, "last", lengths)


def sequence_softmax(x, lengths=None, name=None):
    """reference: sequence_softmax_op.cc — softmax over each valid prefix."""
    def impl(data, *rest):
        lens = rest[0] if rest else None
        T = data.shape[1]
        if lens is None:
            logits = data
        else:
            mask = jnp.arange(T)[None, :] < lens[:, None]
            logits = jnp.where(mask, data, -jnp.inf)
        out = jax.nn.softmax(logits, axis=1)
        return jnp.where(jnp.isfinite(logits), out, 0.0)
    args = (x,) + ((lengths,) if lengths is not None else ())
    return apply("sequence_softmax", impl, *args)


def sequence_reverse(x, lengths=None, name=None):
    """reference: sequence_reverse_op.cc — reverse each valid prefix,
    padding stays in place."""
    def impl(data, *rest):
        lens = rest[0] if rest else None
        T = data.shape[1]
        r = jnp.arange(T)
        if lens is None:
            idx = jnp.broadcast_to(r[::-1], data.shape[:2])
        else:
            rev = lens[:, None] - 1 - r[None, :]
            idx = jnp.where(r[None, :] < lens[:, None], rev, r[None, :])
        ishape = idx.shape + (1,) * (data.ndim - 2)
        return jnp.take_along_axis(
            data, idx.reshape(ishape).astype(jnp.int32), axis=1)
    args = (x,) + ((lengths,) if lengths is not None else ())
    return apply("sequence_reverse", impl, *args)


def sequence_expand(x, y_lengths, ref_level=0, name=None):
    """reference: sequence_expand_op.cc — repeat row i of ``x``
    ``y_lengths[i]`` times along dim 0. Repeat counts are read at trace
    time (static output shape)."""
    reps = np.asarray(_raw(y_lengths)).astype(np.int64)
    idx = np.repeat(np.arange(reps.shape[0]), reps).astype(np.int32)

    def impl(data, lens):
        return data[jnp.asarray(idx)]
    return apply("sequence_expand", impl, x, y_lengths)


def sequence_expand_as(x, y, name=None):
    """reference: sequence_expand_as_op.cc."""
    n = int(_raw(y).shape[0])
    b = int(_raw(x).shape[0])
    if n % b != 0:
        raise ValueError(f"cannot expand {b} rows to {n}")
    rep = n // b

    def impl(data, _):
        return jnp.repeat(data, rep, axis=0)
    return apply("sequence_expand_as", impl, x, y)


def sequence_concat(xs: Sequence, lengths: Sequence, name=None):
    """reference: sequence_concat_op.cc — interleave per-row: row b of the
    result is x1[b][:l1[b]] ++ x2[b][:l2[b]] ++ ..., padded to the summed
    max length. Returns (data, lengths)."""
    raws = [_raw(x) for x in xs]
    lens = [_raw(l) for l in lengths]
    T_out = sum(int(r.shape[1]) for r in raws)

    def impl(*args):
        k = len(raws)
        datas, ls = args[:k], args[k:]
        B = datas[0].shape[0]
        total = ls[0]
        for l in ls[1:]:
            total = total + l
        out_shape = (B, T_out) + datas[0].shape[2:]
        out = jnp.zeros(out_shape, datas[0].dtype)
        offset = jnp.zeros((B,), jnp.int32)
        for d, l in zip(datas, ls):
            T = d.shape[1]
            t_idx = jnp.arange(T)[None, :]
            valid = t_idx < l[:, None]
            dest = offset[:, None] + t_idx
            dest = jnp.where(valid, dest, T_out - 1)
            b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], dest.shape)
            contrib = jnp.where(
                valid.reshape(valid.shape + (1,) * (d.ndim - 2)), d, 0)
            out = out.at[b_idx, dest].add(
                jnp.where(valid.reshape(valid.shape + (1,) * (d.ndim - 2)),
                          contrib, 0))
            offset = offset + l.astype(jnp.int32)
        return out, total
    flat = list(xs) + list(lengths)
    data, total = apply("sequence_concat", impl, *flat)
    return data, total


def sequence_slice(x, offset, length, name=None):
    """reference: sequence_slice_op.cc — per-row slice [offset, offset+len)
    re-packed to the left; returns (data, new_lengths)."""
    T = int(_raw(x).shape[1])

    def impl(data, off, ln):
        t = jnp.arange(T)[None, :]
        src = off[:, None] + t
        src = jnp.clip(src, 0, T - 1)
        gathered = jnp.take_along_axis(
            data, src.reshape(src.shape + (1,) * (data.ndim - 2)).astype(
                jnp.int32), axis=1)
        valid = t < ln[:, None]
        vshape = valid.shape + (1,) * (data.ndim - 2)
        return jnp.where(valid.reshape(vshape), gathered, 0), ln
    data, ln = apply("sequence_slice", impl, x, offset, length)
    return data, ln


def sequence_enumerate(x, win_size, pad_value=0, lengths=None, name=None):
    """reference: sequence_enumerate_op.cc — sliding windows of ids:
    [B, T] -> [B, T, win_size]."""
    w = int(win_size)

    def impl(ids, *rest):
        lens = rest[0] if rest else None
        T = ids.shape[1]
        t = jnp.arange(T)[:, None] + jnp.arange(w)[None, :]   # [T, w]
        limit = (lens[:, None, None] if lens is not None
                 else jnp.asarray(T))
        src = jnp.minimum(t, T - 1)
        vals = ids[:, src]                                     # [B, T, w]
        ok = t[None, :, :] < limit
        return jnp.where(ok, vals, jnp.asarray(pad_value, ids.dtype))
    args = (x,) + ((lengths,) if lengths is not None else ())
    return apply("sequence_enumerate", impl, *args)


def sequence_erase(x, tokens, lengths=None, name=None):
    """reference: sequence_erase_op.cc — remove the listed token ids from
    each row, left-packing survivors; returns (data, new_lengths) with the
    padded shape preserved (masked-ragged form of the LoD shrink)."""
    toks = np.asarray(tokens).reshape(-1)

    def impl(ids, *rest):
        lens = rest[0] if rest else None
        B, T = ids.shape
        t = jnp.arange(T)[None, :]
        valid = t < lens[:, None] if lens is not None else jnp.ones(
            (B, T), bool)
        keep = valid & ~jnp.isin(ids, jnp.asarray(toks, ids.dtype))
        new_len = keep.sum(axis=1).astype(
            lens.dtype if lens is not None else jnp.int64)
        # left-pack surviving tokens: position = exclusive cumsum of keep
        pos = jnp.cumsum(keep, axis=1) - 1
        dest = jnp.where(keep, pos, T - 1)
        out = jnp.zeros_like(ids)
        b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], ids.shape)
        out = out.at[b_idx, dest].max(jnp.where(keep, ids, 0))
        return out, new_len
    args = (x,) + ((lengths,) if lengths is not None else ())
    return apply("sequence_erase", impl, *args)


def sequence_conv(x, weight, bias=None, context_length=3, context_start=None,
                  context_stride=1, lengths=None, name=None):
    """reference: sequence_conv_op.cc — context-window projection: for each
    step, concat [t+start, t+start+length) rows (zeros outside the valid
    prefix) and project by ``weight`` [ctx*D, H]."""
    cl = int(context_length)
    cs = int(context_start) if context_start is not None else -((cl - 1) // 2)

    def impl(data, w, *rest):
        it = iter(rest)
        b = next(it) if bias is not None else None
        lens = next(it, None) if lengths is not None else None
        B, T, D = data.shape
        cols = []
        for k in range(cl):
            off = cs + k
            t = jnp.arange(T) + off
            ok = (t >= 0) & (t < T)
            if lens is not None:
                ok = ok[None, :] & (t[None, :] < lens[:, None])
            else:
                ok = jnp.broadcast_to(ok[None, :], (B, T))
            src = jnp.clip(t, 0, T - 1)
            vals = data[:, src, :]
            cols.append(jnp.where(ok[..., None], vals, 0.0))
        ctx = jnp.concatenate(cols, axis=-1)          # [B, T, cl*D]
        out = ctx @ w
        if b is not None:
            out = out + b
        if lens is not None:
            ok_t = jnp.arange(T)[None, :] < lens[:, None]
            out = jnp.where(ok_t[..., None], out, 0.0)
        return out
    args = [x, weight]
    if bias is not None:
        args.append(bias)
    if lengths is not None:
        args.append(lengths)
    return apply("sequence_conv", impl, *args)


def im2sequence(x, kernels, strides=(1, 1), paddings=(0, 0, 0, 0), name=None):
    """reference: im2sequence_op.cc — NCHW image to patch rows
    [B*out_h*out_w, kh*kw*C]."""
    kh, kw = kernels
    sh, sw = strides

    def impl(img):
        pad = [(0, 0), (0, 0), (paddings[0], paddings[1]),
               (paddings[2], paddings[3])]
        p = jnp.pad(img, pad)
        B, C, H, W = p.shape
        oh = (H - kh) // sh + 1
        ow = (W - kw) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            p, (kh, kw), (sh, sw), "VALID",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))  # [B, C*kh*kw, oh, ow]
        patches = jnp.moveaxis(patches, 1, -1)           # [B, oh, ow, C*kh*kw]
        return patches.reshape(B * oh * ow, C * kh * kw)
    return apply("im2sequence", impl, x)
