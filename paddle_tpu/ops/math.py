"""Math / elementwise / reduction / comparison / search ops.

Parity targets: the reference "Math/elementwise/tensor" operator group
(SURVEY Appendix A; paddle/fluid/operators/elementwise/, reduce_ops/,
activation_op.cc FOR_EACH_ACTIVATION_OP). Each op here is one traceable jnp
implementation registered through dispatch.apply — there is no per-device
kernel matrix; XLA compiles/fuses per use site.
"""
from __future__ import annotations

import sys

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtypes as _dt
from .dispatch import apply, OP_REGISTRY

_this = sys.modules[__name__]


def _axis_arg(axis):
    if isinstance(axis, Tensor):
        a = axis.numpy().tolist()  # noqa: PTA001,PTA002 -- reduction axes are static arguments in XLA; a Tensor axis must be concretized
        return tuple(a) if isinstance(a, list) else int(a)
    if isinstance(axis, (list, tuple)):
        return tuple(int(x) for x in axis)
    return axis


# ---------------------------------------------------------------------------
# Table-driven elementwise unary ops (reference: activation_op.cc and
# per-op .cc files; one line each here).
_UNARY = {
    "abs": jnp.abs, "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log,
    "log2": jnp.log2, "log10": jnp.log10, "log1p": jnp.log1p,
    "sqrt": jnp.sqrt, "rsqrt": jax.lax.rsqrt, "square": jnp.square,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "asin": jnp.arcsin,
    "acos": jnp.arccos, "atan": jnp.arctan, "sinh": jnp.sinh,
    "cosh": jnp.cosh, "tanh": jnp.tanh, "asinh": jnp.arcsinh,
    "acosh": jnp.arccosh, "atanh": jnp.arctanh,
    "floor": jnp.floor, "ceil": jnp.ceil, "round": jnp.round,
    "trunc": jnp.trunc, "sign": jnp.sign, "reciprocal": lambda x: 1.0 / x,
    "erf": jax.lax.erf, "erfinv": jax.lax.erf_inv,
    "lgamma": jax.lax.lgamma, "digamma": jax.lax.digamma,
    "sigmoid": jax.nn.sigmoid, "logsigmoid": jax.nn.log_sigmoid,
    "neg": jnp.negative, "conj": jnp.conj, "angle": jnp.angle,
    "real": jnp.real, "imag": jnp.imag,
    "isnan": jnp.isnan, "isinf": jnp.isinf, "isfinite": jnp.isfinite,
    "logical_not": jnp.logical_not, "bitwise_not": jnp.invert,
    "frac": lambda x: x - jnp.trunc(x),
}

for _name, _fn in _UNARY.items():
    def _make(nm, f):
        def op(x, name=None):
            return apply(nm, f, x)
        op.__name__ = nm
        return op
    setattr(_this, _name, _make(_name, _fn))

# ---------------------------------------------------------------------------
# Binary elementwise (reference: operators/elementwise/elementwise_*_op.cc).
_BINARY = {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "divide": jnp.divide, "floor_divide": jnp.floor_divide,
    "mod": jnp.mod, "remainder": jnp.mod, "floor_mod": jnp.mod,
    "pow_t": jnp.power,
    "maximum": jnp.maximum, "minimum": jnp.minimum,
    "fmax": jnp.fmax, "fmin": jnp.fmin,
    "atan2": jnp.arctan2, "hypot": jnp.hypot,
    "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
    "bitwise_and": jnp.bitwise_and, "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor,
    "equal": lambda a, b: jnp.equal(a, b), "not_equal": jnp.not_equal,
    "greater_than": jnp.greater, "greater_equal": jnp.greater_equal,
    "less_than": jnp.less, "less_equal": jnp.less_equal,
    "nextafter": jnp.nextafter, "copysign": jnp.copysign,
    "heaviside": jnp.heaviside, "gcd": jnp.gcd, "lcm": jnp.lcm,
    "logaddexp": jnp.logaddexp,
}

for _name, _fn in _BINARY.items():
    def _make2(nm, f):
        def op(x, y, name=None):
            return apply(nm, f, x, y)
        op.__name__ = nm
        return op
    setattr(_this, _name, _make2(_name, _fn))


def pow(x, y, name=None):
    return apply("pow", jnp.power, x, y)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """reference: operators/scale_op.cc."""
    def impl(a, s):
        out = a * s + bias if bias_after_scale else (a + bias) * s
        return out
    out = apply("scale", impl, x, scale)
    if act is not None:
        out = getattr(_this, act)(out)
    return out


def clip(x, min=None, max=None, name=None):
    def impl(a, *bounds):
        it = iter(bounds)
        lo = next(it) if isinstance(min, Tensor) else min
        hi = next(it) if isinstance(max, Tensor) else max
        return jnp.clip(a, lo, hi)
    extra = [b for b in (min, max) if isinstance(b, Tensor)]
    return apply("clip", impl, x, *extra)


def lerp(x, y, weight, name=None):
    return apply("lerp", lambda a, b, w: a + w * (b - a), x, y,
                 weight if isinstance(weight, Tensor) else jnp.asarray(weight))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), x)


# ---------------------------------------------------------------------------
# Matrix ops (reference: operators/matmul_v2_op.cc, mul_op.cc, bmm_op.cc,
# addmm_op.cc, mv_op.cc, dot_op.cc, kron_op.cc, cross_op.cc).

def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def impl(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply("matmul_v2", impl, x, y)


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return apply("bmm", jnp.matmul, x, y)


def mv(x, vec, name=None):
    return apply("mv", jnp.matmul, x, vec)


def dot(x, y, name=None):
    return apply("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply("addmm", lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), input, x, y)


def outer(x, y, name=None):
    return apply("outer", lambda a, b: jnp.outer(a, b), x, y)


def inner(x, y, name=None):
    return apply("inner", jnp.inner, x, y)


def kron(x, y, name=None):
    return apply("kron", jnp.kron, x, y)


def cross(x, y, axis=9, name=None):
    def impl(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply("cross", impl, x, y)


def multiplex(inputs, index, name=None):
    """reference: operators/multiplex_op.cc — row-wise select among inputs."""
    return apply("multiplex",
                 lambda idx, *xs: jnp.stack(xs, 0)[idx.reshape(-1),
                                                   jnp.arange(xs[0].shape[0])],
                 index, *inputs)


# ---------------------------------------------------------------------------
# Reductions (reference: operators/reduce_ops/).

def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    d = _dt.convert_dtype(dtype)

    def impl(a):
        out = jnp.sum(a, axis=ax, keepdims=keepdim)
        return out.astype(d) if d is not None else out
    return apply("reduce_sum", impl, x)


def mean(x, axis=None, keepdim=False, name=None):
    return apply("reduce_mean",
                 lambda a: jnp.mean(a, axis=_axis_arg(axis), keepdims=keepdim), x)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)

    def impl(a):
        out = jnp.prod(a, axis=_axis_arg(axis), keepdims=keepdim)
        return out.astype(d) if d is not None else out
    return apply("reduce_prod", impl, x)


def max(x, axis=None, keepdim=False, name=None):
    return apply("reduce_max",
                 lambda a: jnp.max(a, axis=_axis_arg(axis), keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    return apply("reduce_min",
                 lambda a: jnp.min(a, axis=_axis_arg(axis), keepdims=keepdim), x)


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def all(x, axis=None, keepdim=False, name=None):
    return apply("reduce_all",
                 lambda a: jnp.all(a, axis=_axis_arg(axis), keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):
    return apply("reduce_any",
                 lambda a: jnp.any(a, axis=_axis_arg(axis), keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("std", lambda a: jnp.std(a, axis=_axis_arg(axis),
                                          ddof=1 if unbiased else 0, keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("var", lambda a: jnp.var(a, axis=_axis_arg(axis),
                                          ddof=1 if unbiased else 0, keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, name=None):
    return apply("median", lambda a: jnp.median(a, axis=_axis_arg(axis), keepdims=keepdim), x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply("nanmedian", lambda a: jnp.nanmedian(a, axis=_axis_arg(axis), keepdims=keepdim), x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return apply("nansum", lambda a: jnp.nansum(a, axis=_axis_arg(axis), keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply("nanmean", lambda a: jnp.nanmean(a, axis=_axis_arg(axis), keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply("quantile", lambda a: jnp.quantile(a, jnp.asarray(q), axis=_axis_arg(axis),
                                                    keepdims=keepdim), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply("logsumexp",
                 lambda a: jax.scipy.special.logsumexp(a, axis=_axis_arg(axis), keepdims=keepdim), x)


def cumsum(x, axis=None, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)

    def impl(a):
        out = jnp.cumsum(a if axis is not None else a.reshape(-1), axis=axis if axis is not None else 0)
        return out.astype(d) if d is not None else out
    return apply("cumsum", impl, x)


def cumprod(x, dim=None, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)

    def impl(a):
        out = jnp.cumprod(a, axis=dim)
        return out.astype(d) if d is not None else out
    return apply("cumprod", impl, x)


def cummax(x, axis=None, dtype="int64", name=None):
    def impl(a):
        arr = a if axis is not None else a.reshape(-1)
        ax = axis if axis is not None else 0
        vals = jax.lax.associative_scan(jnp.maximum, arr, axis=ax)
        return vals
    return apply("cummax", impl, x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply("count_nonzero",
                 lambda a: jnp.count_nonzero(a, axis=_axis_arg(axis), keepdims=keepdim)
                 .astype(jnp.int64), x)


# ---------------------------------------------------------------------------
# Search / sort (reference: operators/arg_max_op.cc, argsort_op.cc,
# top_k_v2_op.cc, index_select_op.cc, masked_select_op.cc, where_op.cc...).

def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = _dt.convert_dtype(dtype)
    return apply("arg_max",
                 lambda a: jnp.argmax(a, axis=axis, keepdims=keepdim if axis is not None else False)
                 .astype(d), x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = _dt.convert_dtype(dtype)
    return apply("arg_min",
                 lambda a: jnp.argmin(a, axis=axis, keepdims=keepdim if axis is not None else False)
                 .astype(d), x)


def argsort(x, axis=-1, descending=False, name=None):
    def impl(a):
        idx = jnp.argsort(a, axis=axis)
        if descending:
            idx = jnp.flip(idx, axis=axis)
        return idx.astype(jnp.int64)
    return apply("argsort", impl, x)


def sort(x, axis=-1, descending=False, name=None):
    def impl(a):
        out = jnp.sort(a, axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out
    return apply("sort", impl, x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    kk = int(k.item() if isinstance(k, Tensor) else k)  # noqa: PTA002 -- k fixes the output shape and must be concrete

    def impl(a):
        ax = axis if axis is not None else a.ndim - 1
        moved = jnp.moveaxis(a, ax, -1)
        src = moved if largest else -moved
        vals, idx = jax.lax.top_k(src, kk)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax))
    return apply("top_k_v2", impl, x)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def impl(a):
        moved = jnp.moveaxis(a, axis, -1)
        vals = jnp.sort(moved, axis=-1)[..., k - 1]
        idx = jnp.argsort(moved, axis=-1)[..., k - 1]
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx.astype(jnp.int64)
    return apply("kthvalue", impl, x)


def mode(x, axis=-1, keepdim=False, name=None):
    def impl(a):
        moved = jnp.moveaxis(a, axis, -1)
        s = jnp.sort(moved, axis=-1)
        # mode = value with the longest run in the sorted array
        n = s.shape[-1]
        runs = jnp.concatenate([jnp.ones(s.shape[:-1] + (1,), jnp.int32),
                                (s[..., 1:] != s[..., :-1]).astype(jnp.int32)], -1)
        grp = jnp.cumsum(runs, -1)
        counts = jax.vmap(lambda g: jnp.bincount(g.reshape(-1), length=n + 1),
                          in_axes=0)(grp.reshape(-1, n)).reshape(grp.shape[:-1] + (n + 1,))
        best_grp = jnp.argmax(counts, -1)
        pos = jnp.argmax((grp == best_grp[..., None]).astype(jnp.int32), -1)
        vals = jnp.take_along_axis(s, pos[..., None], -1)[..., 0]
        idx = jnp.argmax((moved == vals[..., None]).astype(jnp.int32), -1)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx.astype(jnp.int64)
    return apply("mode", impl, x)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return apply("where", jnp.where, condition, x, y)


def nonzero(x, as_tuple=False):
    """Data-dependent shape: materialized on host (reference where_index op is
    likewise dynamic; under jit use masking instead)."""
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i, jnp.int64)) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1), jnp.int64))


def masked_select(x, mask, name=None):
    """Data-dependent shape: host fallback (reference: masked_select_op.cc)."""
    arr = np.asarray(x._data)
    m = np.asarray(mask._data if isinstance(mask, Tensor) else mask)
    return Tensor(jnp.asarray(arr[m.astype(bool)]))


def masked_fill(x, mask, value, name=None):
    val = value if not isinstance(value, Tensor) else value
    if isinstance(val, Tensor):
        return apply("masked_fill", lambda a, m, v: jnp.where(m, v.astype(a.dtype), a), x, mask, val)
    return apply("masked_fill", lambda a, m: jnp.where(m, jnp.asarray(val, a.dtype), a), x, mask)


def index_select(x, index, axis=0, name=None):
    return apply("index_select", lambda a, i: jnp.take(a, i, axis=axis), x, index)


def index_sample(x, index):
    """reference: operators/index_sample_op.cc — per-row gather."""
    return apply("index_sample",
                 lambda a, i: jnp.take_along_axis(a, i, axis=1), x, index)


def take_along_axis(arr, indices, axis, name=None):
    return apply("take_along_axis",
                 lambda a, i: jnp.take_along_axis(a, i, axis=axis), arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def impl(a, i, v):
        if reduce == "assign":
            return jnp.put_along_axis(a, i, v.astype(a.dtype), axis=axis, inplace=False)
        if reduce == "add":
            return a.at[_along_axis_index(a, i, axis)].add(v.astype(a.dtype))
        if reduce in ("mul", "multiply"):
            return a.at[_along_axis_index(a, i, axis)].multiply(v.astype(a.dtype))
        raise ValueError(reduce)
    return apply("put_along_axis", impl, arr, indices,
                 values if isinstance(values, Tensor) else Tensor(values))


def _along_axis_index(a, i, axis):
    idx = list(jnp.meshgrid(*[jnp.arange(s) for s in i.shape], indexing="ij"))
    idx[axis] = i
    return tuple(idx)


def gather(x, index, axis=0, name=None):
    ax = int(axis.item() if isinstance(axis, Tensor) else axis)  # noqa: PTA002 -- gather axis is a static argument in XLA
    return apply("gather", lambda a, i: jnp.take(a, i.reshape(-1) if i.ndim > 1 else i,
                                                 axis=ax), x, index)


def gather_nd(x, index, name=None):
    def impl(a, i):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a[idx]
    return apply("gather_nd", impl, x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    def impl(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u.astype(a.dtype))
        z = a.at[i].set(jnp.zeros_like(u, a.dtype))
        return z.at[i].add(u.astype(a.dtype))
    return apply("scatter", impl, x, index, updates)


def scatter_nd_add(x, index, updates, name=None):
    def impl(a, i, u):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a.at[idx].add(u.astype(a.dtype))
    return apply("scatter_nd_add", impl, x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    def impl(i, u):
        a = jnp.zeros(shape, u.dtype)
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a.at[idx].add(u)
    return apply("scatter_nd", impl, index, updates)


def bincount(x, weights=None, minlength=0, name=None):
    arr = np.asarray(x._data)
    w = None if weights is None else np.asarray(weights._data)
    return Tensor(jnp.asarray(np.bincount(arr, w, minlength)))


def histogram(input, bins=100, min=0, max=0, name=None):
    def impl(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
        return h.astype(jnp.int64)
    return apply("histogram", impl, input)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    """Data-dependent output shape — host computation (reference unique op is
    CPU-only for the same reason)."""
    arr = np.asarray(x._data)
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not (return_index or return_inverse or return_counts):
        return Tensor(jnp.asarray(res))
    out = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(out)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    """Data-dependent output shape — host computation, like `unique`."""
    arr = np.asarray(x._data)
    if axis is None:
        flat = arr.reshape(-1)
        if flat.size == 0:
            keep = np.zeros(0, bool)
        else:
            keep = np.concatenate([[True], flat[1:] != flat[:-1]])
        vals = flat[keep]
        group = np.cumsum(keep) - 1
        counts = np.bincount(group, minlength=len(vals)).astype(np.int64)
        inverse = group.astype(np.int64)
    else:
        moved = np.moveaxis(arr, axis, 0)
        flatrows = moved.reshape(moved.shape[0], -1)
        if flatrows.shape[0] == 0:
            keep = np.zeros(0, bool)
        else:
            keep = np.concatenate([[True], np.any(flatrows[1:] != flatrows[:-1], axis=1)])
        vals = np.moveaxis(moved[keep], 0, axis)
        group = np.cumsum(keep) - 1
        counts = np.bincount(group, minlength=int(keep.sum())).astype(np.int64)
        inverse = group.astype(np.int64)
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        outs.append(Tensor(jnp.asarray(inverse)))
    if return_counts:
        outs.append(Tensor(jnp.asarray(counts)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("allclose",
                 lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                 x, y)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("isclose",
                 lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                 x, y)


def equal_all(x, y, name=None):
    return apply("equal_all", lambda a, b: jnp.array_equal(a, b), x, y)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    return apply("searchsorted",
                 lambda s, v: jnp.searchsorted(s, v, side="right" if right else "left")
                 .astype(jnp.int32 if out_int32 else jnp.int64), sorted_sequence, values)


# ---------------------------------------------------------------------------
# Norms (reference: operators/p_norm_op.cc, frobenius_norm_op.cc,
# squared_l2_norm_op.cc, clip_by_norm_op.cc, dist_op.cc).

def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def impl(a):
        if p == "fro" and axis is None:
            return jnp.sqrt(jnp.sum(a * a))
        if p == "fro":
            return jnp.sqrt(jnp.sum(a * a, axis=_axis_arg(axis), keepdims=keepdim))
        if p == np.inf or p == "inf":
            return jnp.max(jnp.abs(a), axis=_axis_arg(axis), keepdims=keepdim)
        if p == -np.inf or p == "-inf":
            return jnp.min(jnp.abs(a), axis=_axis_arg(axis), keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=_axis_arg(axis), keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=_axis_arg(axis),
                                 keepdims=keepdim), 1.0 / p)
    return apply("p_norm", impl, x)


def dist(x, y, p=2, name=None):
    def impl(a, b):
        d = jnp.abs(a - b)
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype))
        if p == np.inf:
            return jnp.max(d)
        if p == -np.inf:
            return jnp.min(d)
        return jnp.power(jnp.sum(jnp.power(d, p)), 1.0 / p)
    return apply("dist", impl, x, y)


def clip_by_norm(x, max_norm, name=None):
    def impl(a):
        n = jnp.sqrt(jnp.sum(a * a))
        return jnp.where(n > max_norm, a * (max_norm / n), a)
    return apply("clip_by_norm", impl, x)


def squared_l2_norm(x):
    return apply("squared_l2_norm", lambda a: jnp.sum(a * a), x)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("trace", lambda a: jnp.trace(a, offset, axis1, axis2), x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply("nan_to_num",
                 lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x)


def increment(x, value=1.0, name=None):
    out = apply("increment", lambda a: a + value, x)
    x._swap_payload(out)
    return x


# -- round-3 long tail (reference: operators/ activation_op.cc, cum_op.cc,
# cos_sim_op.cc, shard_index_op.cc, etc.) ------------------------------------

def logit(x, eps=None, name=None):
    """reference: operators/logit_op.cc."""
    def impl(a):
        z = jnp.clip(a, eps, 1.0 - eps) if eps is not None else a
        return jnp.log(z / (1.0 - z))
    return apply("logit", impl, x)


def rad2deg(x, name=None):
    return apply("rad2deg", lambda a: a * (180.0 / np.pi), x)


def deg2rad(x, name=None):
    return apply("deg2rad", lambda a: a * (np.pi / 180.0), x)


def ldexp(x, y, name=None):
    return apply("ldexp", lambda a, b: a * jnp.power(
        jnp.asarray(2.0, a.dtype if jnp.issubdtype(a.dtype, jnp.floating)
                    else jnp.float32), b.astype(jnp.float32)), x, y)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    """reference: operators/diff_op (paddle.diff)."""
    def impl(a, *extra):
        it = iter(extra)
        pre = next(it) if prepend is not None else None
        app = next(it) if append is not None else None
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)
    args = [x] + [t for t in (prepend, append) if t is not None]
    return apply("diff", impl, *args)


def cummin(x, axis=None, dtype="int64", name=None):
    """reference: cum_op.cc cummin — returns (values, indices); indices
    track WHERE the running minimum was set (earliest on ties)."""
    def impl(a):
        ax = axis if axis is not None else 0
        arr = a.reshape(-1) if axis is None else a
        idx = jnp.arange(arr.shape[ax]).reshape(
            [-1 if i == (ax % arr.ndim) else 1 for i in range(arr.ndim)])
        idx = jnp.broadcast_to(idx, arr.shape).astype(jnp.int32)

        def comb(lhs, rhs):
            lv, li = lhs
            rv, ri = rhs
            take_r = rv < lv  # strict: earliest index wins ties
            return (jnp.where(take_r, rv, lv), jnp.where(take_r, ri, li))

        vals, imin = jax.lax.associative_scan(comb, (arr, idx), axis=ax)
        return vals, imin.astype(np.dtype(dtype))
    return apply("cummin", impl, x)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    """reference: cum_op.cc logcumsumexp."""
    def impl(a):
        ax = axis if axis is not None else 0
        arr = a.reshape(-1) if axis is None else a

        def comb(p, q):
            return jnp.logaddexp(p, q)
        return jax.lax.associative_scan(comb, arr, axis=ax)
    return apply("logcumsumexp", impl, x)


def vander(x, n=None, increasing=False, name=None):
    def impl(a):
        return jnp.vander(a, N=n, increasing=increasing)
    return apply("vander", impl, x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    def impl(a, *ws):
        it = iter(ws)
        fw = next(it) if fweights is not None else None
        aw = next(it) if aweights is not None else None
        return jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                       fweights=fw, aweights=aw)
    args = [x] + [w for w in (fweights, aweights) if w is not None]
    return apply("cov", impl, *args)


def corrcoef(x, rowvar=True, name=None):
    def impl(a):
        return jnp.corrcoef(a, rowvar=rowvar)
    return apply("corrcoef", impl, x)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    """reference: searchsorted family (paddle.bucketize)."""
    def impl(a, s):
        side = "right" if right else "left"
        out = jnp.searchsorted(s, a, side=side)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply("bucketize", impl, x, sorted_sequence)


digitize = bucketize


def take(x, index, mode="raise", name=None):
    """reference: paddle.take — flat-index gather with clip/wrap modes."""
    def impl(a, idx):
        flat = a.reshape(-1)
        n = flat.shape[0]
        if mode == "wrap":
            ii = jnp.mod(idx, n)
        else:  # raise/clip both clamp under jit (no host assert)
            ii = jnp.clip(idx, -n, n - 1)
        ii = jnp.where(ii < 0, ii + n, ii)
        return flat[ii]
    return apply("take", impl, x, index)


def index_add(x, index, axis, value, name=None):
    """reference: paddle.index_add — x.at[..., index, ...] += value."""
    def impl(a, idx, v):
        moved = jnp.moveaxis(a, axis, 0)
        vm = jnp.moveaxis(v, axis, 0)
        out = moved.at[idx].add(vm)
        return jnp.moveaxis(out, 0, axis)
    return apply("index_add", impl, x, index, value)


def index_put(x, indices, value, accumulate=False, name=None):
    """reference: paddle.index_put."""
    def impl(a, *rest):
        *idxs, v = rest
        ii = tuple(idxs)
        if accumulate:
            return a.at[ii].add(v)
        return a.at[ii].set(v)
    return apply("index_put", impl, x, *list(indices), value)


def index_fill(x, index, axis, fill_value, name=None):
    def impl(a, idx):
        moved = jnp.moveaxis(a, axis, 0)
        out = moved.at[idx].set(jnp.asarray(fill_value, a.dtype))
        return jnp.moveaxis(out, 0, axis)
    return apply("index_fill", impl, x, index)


def renorm(x, p, axis, max_norm, name=None):
    """reference: operators/renorm_op.cc — clamp each sub-tensor's p-norm."""
    def impl(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.power(jnp.sum(jnp.power(jnp.abs(flat), p), axis=1),
                          1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        out = flat * factor[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)
    return apply("renorm", impl, x)


def cos_sim(X, Y, name=None):
    """reference: operators/cos_sim_op.cc — row-wise cosine similarity."""
    def impl(a, b):
        a2 = a.reshape(a.shape[0], -1)
        b2 = jnp.broadcast_to(b.reshape(b.shape[0], -1),
                              (a.shape[0], a.reshape(a.shape[0], -1).shape[1]))
        num = jnp.sum(a2 * b2, axis=1)
        den = jnp.sqrt(jnp.sum(a2 * a2, axis=1)) * \
            jnp.sqrt(jnp.sum(b2 * b2, axis=1))
        return (num / jnp.maximum(den, 1e-12))[:, None]
    return apply("cos_sim", impl, X, Y)


def l1_norm(x, name=None):
    """reference: operators/l1_norm_op.cc."""
    return apply("l1_norm", lambda a: jnp.sum(jnp.abs(a)), x)


def frobenius_norm(x, axis=None, keepdim=False, name=None):
    """reference: reduce_ops/frobenius_norm_op.cc."""
    def impl(a):
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdim))
    return apply("frobenius_norm", impl, x)


def where_index(condition, name=None):
    """reference: operators/where_index_op.cc (nonzero coordinates). Output
    is data-dependent so the result is computed eagerly via numpy — usable
    outside jit only (the reference op is likewise host-side dynamic)."""
    from ..core.tensor import Tensor
    cond_np = np.asarray(condition._data if isinstance(condition, Tensor)
                         else condition)
    return Tensor(np.stack(np.nonzero(cond_np), axis=1).astype(np.int64))


def unflatten(x, axis, shape, name=None):
    def impl(a):
        new_shape = (a.shape[:axis % a.ndim] + tuple(shape)
                     + a.shape[axis % a.ndim + 1:])
        return a.reshape(new_shape)
    return apply("unflatten", impl, x)
