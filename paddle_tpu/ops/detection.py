"""Detection ops: yolo_box / yolov3_loss / multiclass_nms / prior_box /
box_coder / iou_similarity / box_clip.

TPU-native equivalents of the reference detection op family
(reference: paddle/fluid/operators/detection/yolo_box_op.cc,
yolov3_loss_op.cc, multiclass_nms_op.cc, prior_box_op.cc, box_coder_op.cc,
iou_similarity_op.cc, box_clip_op.cc).

Dynamic-shape strategy (SURVEY §7 hard part; the reference emits LoD
tensors of ragged size): every op here has a FIXED-size output with an
explicit validity convention —
- ground-truth boxes arrive padded to a constant slot count, zero-area
  slots are ignored;
- multiclass_nms returns exactly ``keep_top_k`` rows per image, invalid
  rows carry label -1 (callers mask on label >= 0) plus an explicit count.
This keeps one compiled XLA program per shape bucket instead of per input.
Most ops are pure jnp/lax compositions — XLA fuses them. The greedy NMS
scan additionally ships as a hand-written Pallas kernel
(ops/custom.py pallas_greedy_nms — IoU matrix + kept-mask stay
VMEM/register resident across the sequential loop), equivalence-tested
against the lax.scan form here.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .dispatch import apply
from ..core.tensor import Tensor

__all__ = ["yolo_box", "yolov3_loss", "multiclass_nms", "prior_box",
           "box_coder", "iou_similarity", "box_clip",
           "roi_align", "roi_pool", "anchor_generator",
           "generate_proposals", "distribute_fpn_proposals",
           "collect_fpn_proposals", "bipartite_match", "target_assign",
           "box_decoder_and_assign", "polygon_box_transform", "smooth_l1",
           "matrix_nms", "density_prior_box"]


def _sigmoid(x):
    return jax.nn.sigmoid(x)


# -- yolo_box -----------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    """reference: detection/yolo_box_op.cc (GetYoloBox/CalcDetectionBox).

    x: [N, A*(5+C), H, W]; img_size: [N, 2] (h, w).
    Returns boxes [N, A*H*W, 4] (x1y1x2y2 in image scale) and scores
    [N, A*H*W, C]; boxes with conf < conf_thresh are zeroed.
    """
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = anchors.shape[0]
    C = int(class_num)

    def impl(xr, img):
        n, _, h, w = xr.shape
        p = xr.reshape(n, A, 5 + C, h, w)
        grid_x = jnp.arange(w, dtype=xr.dtype).reshape(1, 1, 1, w)
        grid_y = jnp.arange(h, dtype=xr.dtype).reshape(1, 1, h, 1)
        alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
        bx = (_sigmoid(p[:, :, 0]) * alpha + beta + grid_x) / w
        by = (_sigmoid(p[:, :, 1]) * alpha + beta + grid_y) / h
        input_h = h * downsample_ratio
        input_w = w * downsample_ratio
        an_w = (anchors[:, 0] / input_w).reshape(1, A, 1, 1).astype(xr.dtype)
        an_h = (anchors[:, 1] / input_h).reshape(1, A, 1, 1).astype(xr.dtype)
        bw = jnp.exp(p[:, :, 2]) * an_w
        bh = jnp.exp(p[:, :, 3]) * an_h
        conf = _sigmoid(p[:, :, 4])
        keep = conf >= conf_thresh
        img_h = img[:, 0].astype(xr.dtype).reshape(n, 1, 1, 1)
        img_w = img[:, 1].astype(xr.dtype).reshape(n, 1, 1, 1)
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
            x2 = jnp.clip(x2, 0, img_w - 1)
            y2 = jnp.clip(y2, 0, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
        boxes = jnp.where(keep[..., None], boxes, 0.0)
        scores = conf[..., None] * _sigmoid(
            jnp.moveaxis(p[:, :, 5:], 2, -1))
        scores = jnp.where(keep[..., None], scores, 0.0)
        # [N, A, H, W, k] -> [N, A*H*W, k]
        return (boxes.reshape(n, A * h * w, 4),
                scores.reshape(n, A * h * w, C))
    return apply("yolo_box", impl, x, img_size)


# -- iou helpers --------------------------------------------------------------

def _pairwise_iou(a, b, normalized=True):
    """a [M,4], b [K,4] x1y1x2y2 -> [M,K]. Unnormalized (pixel) boxes get
    the reference's +1 extent offset (JaccardOverlap, detection/nms_util.h)."""
    off = 0.0 if normalized else 1.0
    area_a = jnp.clip(a[:, 2] - a[:, 0] + off, 0, None) * \
        jnp.clip(a[:, 3] - a[:, 1] + off, 0, None)
    area_b = jnp.clip(b[:, 2] - b[:, 0] + off, 0, None) * \
        jnp.clip(b[:, 3] - b[:, 1] + off, 0, None)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt + off, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def iou_similarity(x, y, box_normalized=True, name=None):
    """reference: detection/iou_similarity_op.cc — [M,4]x[K,4] -> [M,K]."""
    return apply("iou_similarity",
                 lambda a, b: _pairwise_iou(a, b, normalized=box_normalized),
                 x, y)


def box_clip(input, im_info, name=None):
    """reference: detection/box_clip_op.cc — clip to [0, dim-1]."""
    def impl(boxes, info):
        h, w = info[0], info[1]
        return jnp.stack([
            jnp.clip(boxes[..., 0], 0, w - 1),
            jnp.clip(boxes[..., 1], 0, h - 1),
            jnp.clip(boxes[..., 2], 0, w - 1),
            jnp.clip(boxes[..., 3], 0, h - 1)], axis=-1)
    return apply("box_clip", impl, input, im_info)


# -- multiclass_nms -----------------------------------------------------------

def _greedy_nms_mask(boxes, scores, iou_threshold, score_threshold, top_k,
                     normalized=True, nms_eta=1.0):
    """Greedy per-class suppression over score-sorted candidates.
    Returns (kept mask over the top_k sorted slots, their indices).
    ``nms_eta < 1`` decays the threshold after each kept box while it stays
    above 0.5 (reference: detection/nms_util.h NMSFast adaptive_threshold)."""
    k = min(top_k, scores.shape[0])
    top_scores, order = lax.top_k(scores, k)
    cand = boxes[order]
    iou = _pairwise_iou(cand, cand, normalized=normalized)
    valid = top_scores > score_threshold
    adaptive = nms_eta < 1.0

    def step(carry, i):
        kept, thr = carry
        # suppressed if any higher-scored kept candidate overlaps too much
        sup = jnp.any(kept & (iou[:, i] > thr) & (jnp.arange(k) < i))
        keep_i = valid[i] & ~sup
        if adaptive:
            thr = jnp.where(keep_i & (thr > 0.5), thr * nms_eta, thr)
        return (kept.at[i].set(keep_i), thr), keep_i

    kept0 = jnp.zeros(k, bool)
    thr0 = jnp.asarray(iou_threshold, jnp.float32)
    (kept, _), _ = lax.scan(step, (kept0, thr0), jnp.arange(k))
    return kept, order, top_scores


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None,
                   return_index=False):
    """reference: detection/multiclass_nms_op.cc (MultiClassNMS kernel).

    bboxes: [N, M, 4]; scores: [N, C, M].
    Fixed-size output: out [N, keep_top_k, 6] rows = (label, score,
    x1, y1, x2, y2), padded rows have label -1; counts [N] = valid rows
    (the reference's LoD offsets → explicit count vector).
    """
    def impl(bb, sc):
        n, c, m = sc.shape

        def per_image(boxes, cls_scores):
            labels_all, scores_all, boxes_all = [], [], []
            for cls in range(c):
                if cls == background_label:
                    continue
                kept, order, top_scores = _greedy_nms_mask(
                    boxes, cls_scores[cls], nms_threshold,
                    score_threshold, nms_top_k,
                    normalized=normalized, nms_eta=nms_eta)
                scores = jnp.where(kept, top_scores, -1.0)
                labels_all.append(jnp.full_like(scores, cls))
                scores_all.append(scores)
                boxes_all.append(boxes[order])
            all_scores = jnp.concatenate(scores_all)
            all_labels = jnp.concatenate(labels_all)
            all_boxes = jnp.concatenate(boxes_all, axis=0)
            kk = min(keep_top_k, all_scores.shape[0])
            best, idx = lax.top_k(all_scores, kk)
            valid = best >= 0
            out = jnp.concatenate([
                jnp.where(valid, all_labels[idx], -1.0)[:, None],
                jnp.where(valid, best, 0.0)[:, None],
                jnp.where(valid[:, None], all_boxes[idx], 0.0)], axis=1)
            if kk < keep_top_k:
                pad = jnp.zeros((keep_top_k - kk, 6), out.dtype)
                pad = pad.at[:, 0].set(-1.0)
                out = jnp.concatenate([out, pad], axis=0)
            return out, valid.sum()

        outs, counts = jax.vmap(per_image)(bb, sc)
        return outs, counts.astype(jnp.int32)
    return apply("multiclass_nms", impl, bboxes, scores)


# -- prior_box ----------------------------------------------------------------

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """reference: detection/prior_box_op.cc (SSD prior boxes)."""
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    min_sizes = [float(s) for s in np.atleast_1d(min_sizes)]
    max_sizes = [float(s) for s in np.atleast_1d(max_sizes)] if max_sizes \
        else []
    num_priors = len(ars) * len(min_sizes) + len(max_sizes)

    def impl(feat, img):
        fh, fw = feat.shape[2], feat.shape[3]
        ih, iw = img.shape[2], img.shape[3]
        step_h = steps[1] if steps[1] > 0 else ih / fh
        step_w = steps[0] if steps[0] > 0 else iw / fw
        cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
        cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
        cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")
        whs = []
        for ms in min_sizes:
            if min_max_aspect_ratios_order:
                whs.append((ms, ms))
                if max_sizes:
                    mx = max_sizes[min_sizes.index(ms)]
                    whs.append((float(np.sqrt(ms * mx)),) * 2)
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            else:
                for ar in ars:
                    whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
                if max_sizes:
                    mx = max_sizes[min_sizes.index(ms)]
                    whs.append((float(np.sqrt(ms * mx)),) * 2)
        wh = jnp.asarray(whs, jnp.float32)  # [P, 2]
        boxes = jnp.stack([
            (cxg[..., None] - wh[:, 0] / 2) / iw,
            (cyg[..., None] - wh[:, 1] / 2) / ih,
            (cxg[..., None] + wh[:, 0] / 2) / iw,
            (cyg[..., None] + wh[:, 1] / 2) / ih], axis=-1)  # [H, W, P, 4]
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               boxes.shape)
        return boxes, var
    return apply("prior_box", impl, input, image)


def box_coder(prior_box_t, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """reference: detection/box_coder_op.cc."""
    norm = 1.0 if box_normalized else 0.0

    def _cwh(b):
        w = b[..., 2] - b[..., 0] + (1.0 - norm)
        h = b[..., 3] - b[..., 1] + (1.0 - norm)
        cx = b[..., 0] + 0.5 * w
        cy = b[..., 1] + 0.5 * h
        return cx, cy, w, h

    if code_type == "encode_center_size":
        def impl(prior, pvar, target):
            pcx, pcy, pw, ph = _cwh(prior)           # [M,...]
            tcx, tcy, tw, th = _cwh(target[:, None, :] if target.ndim == 2
                                    else target)
            tx = (tcx - pcx) / pw
            ty = (tcy - pcy) / ph
            tw_ = jnp.log(jnp.abs(tw / pw))
            th_ = jnp.log(jnp.abs(th / ph))
            out = jnp.stack([tx, ty, tw_, th_], axis=-1)
            if pvar is not None:
                out = out / pvar
            return out
    else:  # decode_center_size
        def impl(prior, pvar, target):
            pcx, pcy, pw, ph = _cwh(prior)
            t = target
            if pvar is not None:
                t = t * pvar
            ocx = t[..., 0] * pw + pcx
            ocy = t[..., 1] * ph + pcy
            ow = jnp.exp(t[..., 2]) * pw
            oh = jnp.exp(t[..., 3]) * ph
            return jnp.stack([ocx - ow / 2, ocy - oh / 2,
                              ocx + ow / 2 - (1.0 - norm),
                              ocy + oh / 2 - (1.0 - norm)], axis=-1)
    return apply("box_coder", impl, prior_box_t, prior_box_var, target_box)


# -- yolov3_loss --------------------------------------------------------------

def _bce(pred_logit, target):
    p = _sigmoid(pred_logit)
    eps = 1e-7
    return -(target * jnp.log(p + eps) + (1 - target) * jnp.log(1 - p + eps))


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=False, name=None, scale_x_y=1.0):
    """reference: detection/yolov3_loss_op.cc.

    x: [N, A*(5+C), H, W] raw predictions for this scale;
    gt_box: [N, B, 4] (cx, cy, w, h normalized to [0,1]), zero-padded slots;
    gt_label: [N, B] int; anchors: full anchor list (pairs); anchor_mask:
    indices of this scale's anchors. Loss per the YOLOv3 paper: BCE on
    x/y/objectness/class, squared error on w/h, box-size weighting
    (2 - w*h), no-object loss ignored where best-gt IoU > ignore_thresh.
    """
    all_anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask = list(anchor_mask)
    A = len(mask)
    C = int(class_num)

    def impl(xr, gbox, glabel):
        n, _, h, w = xr.shape
        p = xr.reshape(n, A, 5 + C, h, w)
        input_h = float(h * downsample_ratio)
        input_w = float(w * downsample_ratio)
        masked = all_anchors[mask] / np.array([input_w, input_h], np.float32)
        an_w = jnp.asarray(masked[:, 0])      # [A] normalized
        an_h = jnp.asarray(masked[:, 1])

        valid = (gbox[..., 2] > 0) & (gbox[..., 3] > 0)      # [N, B]

        # -- best anchor per gt (shape-only IoU vs ALL anchors) ----------
        all_norm = jnp.asarray(
            all_anchors / np.array([input_w, input_h], np.float32))
        gw = gbox[..., 2][..., None]                          # [N,B,1]
        gh = gbox[..., 3][..., None]
        inter = jnp.minimum(gw, all_norm[:, 0]) * jnp.minimum(gh, all_norm[:, 1])
        union = gw * gh + all_norm[:, 0] * all_norm[:, 1] - inter
        shape_iou = inter / (union + 1e-9)                    # [N,B,Atot]
        best_anchor = jnp.argmax(shape_iou, axis=-1)          # [N,B]
        # position in this scale's mask (-1 if not ours)
        mask_arr = jnp.asarray(mask)
        in_mask = best_anchor[..., None] == mask_arr          # [N,B,A]
        local_a = jnp.argmax(in_mask, axis=-1)                # [N,B]
        responsible = valid & jnp.any(in_mask, axis=-1)

        gi = jnp.clip((gbox[..., 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gbox[..., 1] * h).astype(jnp.int32), 0, h - 1)

        # targets
        tx = gbox[..., 0] * w - gi
        ty = gbox[..., 1] * h - gj
        tw = jnp.log(gbox[..., 2] / (an_w[local_a] + 1e-9) + 1e-9)
        th = jnp.log(gbox[..., 3] / (an_h[local_a] + 1e-9) + 1e-9)
        box_w = 2.0 - gbox[..., 2] * gbox[..., 3]             # size weight

        # gather predictions at assigned cells: [N, B, ...]
        bidx = jnp.arange(n)[:, None]
        px = p[bidx, local_a, 0, gj, gi]
        py = p[bidx, local_a, 1, gj, gi]
        pw = p[bidx, local_a, 2, gj, gi]
        ph = p[bidx, local_a, 3, gj, gi]
        pcls = jnp.moveaxis(p[:, :, 5:], 2, -1)[bidx, local_a, gj, gi]

        rmask = responsible.astype(xr.dtype)
        loss_xy = (_bce(px, tx) + _bce(py, ty)) * box_w * rmask
        loss_wh = ((pw - tw) ** 2 + (ph - th) ** 2) * 0.5 * box_w * rmask
        smooth = 1.0 / max(C, 1) if use_label_smooth else 0.0
        onehot = jax.nn.one_hot(glabel, C) * (1 - 2 * smooth) + smooth
        loss_cls = jnp.sum(_bce(pcls, onehot), axis=-1) * rmask

        # objectness: target 1 at responsible cells; 0 elsewhere unless the
        # predicted box overlaps some gt above ignore_thresh
        obj_logit = p[:, :, 4]                                # [N,A,H,W]
        tobj = jnp.zeros((n, A, h, w), xr.dtype)
        tobj = tobj.at[bidx, local_a, gj, gi].max(rmask)

        # predicted boxes for ignore mask (no grad needed; detached values)
        grid_x = jnp.arange(w, dtype=xr.dtype).reshape(1, 1, 1, w)
        grid_y = jnp.arange(h, dtype=xr.dtype).reshape(1, 1, h, 1)
        bx = (_sigmoid(p[:, :, 0]) + grid_x) / w
        by = (_sigmoid(p[:, :, 1]) + grid_y) / h
        bw = jnp.exp(jnp.clip(p[:, :, 2], -10, 10)) * an_w.reshape(1, A, 1, 1)
        bh = jnp.exp(jnp.clip(p[:, :, 3], -10, 10)) * an_h.reshape(1, A, 1, 1)
        pred_xyxy = jnp.stack([bx - bw / 2, by - bh / 2,
                               bx + bw / 2, by + bh / 2], -1)  # [N,A,H,W,4]
        g_xyxy = jnp.stack([gbox[..., 0] - gbox[..., 2] / 2,
                            gbox[..., 1] - gbox[..., 3] / 2,
                            gbox[..., 0] + gbox[..., 2] / 2,
                            gbox[..., 1] + gbox[..., 3] / 2], -1)  # [N,B,4]

        def img_iou(pb, gb, v):
            i = _pairwise_iou(pb.reshape(-1, 4), gb)          # [AHW, B]
            i = jnp.where(v[None, :], i, 0.0)
            return i.max(axis=-1).reshape(A, h, w)
        best_iou = jax.vmap(img_iou)(lax.stop_gradient(pred_xyxy),
                                     g_xyxy, valid)
        noobj_mask = ((best_iou < ignore_thresh) & (tobj < 0.5)
                      ).astype(xr.dtype)
        loss_obj = (_bce(obj_logit, jnp.ones_like(tobj)) * tobj
                    + _bce(obj_logit, jnp.zeros_like(tobj)) * noobj_mask)

        per_img = (loss_xy.sum(axis=1) + loss_wh.sum(axis=1)
                   + loss_cls.sum(axis=1)
                   + loss_obj.sum(axis=(1, 2, 3)))
        return per_img
    if gt_score is not None:
        return apply("yolov3_loss", lambda a, b, c, s: impl(a, b, c),
                     x, gt_box, gt_label, gt_score)
    return apply("yolov3_loss", impl, x, gt_box, gt_label)


# -- roi ops ------------------------------------------------------------------

def roi_align(input, rois, output_size, spatial_scale=1.0, sampling_ratio=-1,
              rois_num=None, aligned=True, name=None):
    """reference: operators/roi_align_op.cc. input [N,C,H,W]; rois [R,4]
    (x1,y1,x2,y2 in input-image coords); ``rois_num`` [N] maps rois to
    batch images (LoD replacement). Output [R, C, ph, pw].

    ``sampling_ratio=-1`` matches the reference's adaptive per-bin grid
    of ceil(roi_extent / pooled_size) taps. The adaptive count is a
    data-dependent *value*, not shape: taps are laid out on a static
    grid of min(ceil(H/ph), 8) x min(ceil(W/pw), 8) (a trace-time
    constant), positioned per ROI by its actual grid count and masked
    beyond it — exact reference numerics with XLA-static shapes for any
    bin needing <=8 taps per axis (an ROI up to 8x the output size;
    beyond that the taps become a uniform 8-per-axis subsample of the
    bin, still unbiased, bounding compute/memory at 64 taps/bin)."""
    if isinstance(output_size, int):
        ph = pw = int(output_size)
    else:
        ph, pw = output_size
    roff = 0.5 if aligned else 0.0
    if rois_num is not None:
        rn = np.asarray(rois_num._data if isinstance(rois_num, Tensor)
                        else rois_num)
        batch_of = np.repeat(np.arange(rn.shape[0]), rn).astype(np.int32)
    else:
        batch_of = None

    def impl(feat, boxes):
        N, C, H, W = feat.shape
        R = boxes.shape[0]
        bidx = (jnp.asarray(batch_of) if batch_of is not None
                else jnp.zeros((R,), jnp.int32))
        b = boxes * spatial_scale - roff
        x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        if sampling_ratio > 0:
            Gy = Gx = sampling_ratio
            gy = jnp.full_like(bin_h, sampling_ratio)
            gx = jnp.full_like(bin_w, sampling_ratio)
        else:
            # adaptive ceil(bin_extent) taps on a static grid bounded by
            # the feature-map extent and the documented 8-tap/axis cap
            Gy = min(8, max(1, int(np.ceil(H / ph))))
            Gx = min(8, max(1, int(np.ceil(W / pw))))
            gy = jnp.clip(jnp.ceil(bin_h), 1, Gy)
            gx = jnp.clip(jnp.ceil(bin_w), 1, Gx)
        # per-ROI tap offsets within a bin: (s + 0.5)/g for s < g
        offy = (jnp.arange(Gy)[None, :] + 0.5) / gy[:, None]   # [R,Gy]
        offx = (jnp.arange(Gx)[None, :] + 0.5) / gx[:, None]   # [R,Gx]
        my = jnp.arange(Gy)[None, :] < gy[:, None]             # [R,Gy]
        mx = jnp.arange(Gx)[None, :] < gx[:, None]             # [R,Gx]
        iy = jnp.arange(ph)[None, :, None] + offy[:, None, :]  # [R,ph,Gy]
        ix = jnp.arange(pw)[None, :, None] + offx[:, None, :]  # [R,pw,Gx]
        ys = y1[:, None, None] + bin_h[:, None, None] * iy     # [R,ph,Gy]
        xs = x1[:, None, None] + bin_w[:, None, None] * ix     # [R,pw,Gx]

        def bilinear(img, yy, xx):
            # img [C,H,W]; yy [ph,sr]; xx [pw,sr] -> [C,ph,sr,pw,sr]
            # points in (-1, 0) are clamped to 0 BEFORE the corner split
            # (reference roi_align_op kernel: `if (y <= 0) y = 0`), so the
            # border band interpolates within the image, not across it
            oky = (yy >= -1) & (yy <= H)
            okx = (xx >= -1) & (xx <= W)
            yy = jnp.clip(yy, 0.0, float(H - 1))
            xx = jnp.clip(xx, 0.0, float(W - 1))
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            wy1 = yy - y0
            wx1 = xx - x0
            y0i = y0.astype(jnp.int32)
            y1i = jnp.clip(y0i + 1, 0, H - 1)
            x0i = x0.astype(jnp.int32)
            x1i = jnp.clip(x0i + 1, 0, W - 1)

            def g(yi, xi):
                return img[:, yi][:, :, :, xi]      # [C,ph,sr,pw,sr]
            v = (g(y0i, x0i) * ((1 - wy1)[None, :, :, None, None]
                                * (1 - wx1)[None, None, None, :, :])
                 + g(y1i, x0i) * (wy1[None, :, :, None, None]
                                  * (1 - wx1)[None, None, None, :, :])
                 + g(y0i, x1i) * ((1 - wy1)[None, :, :, None, None]
                                  * wx1[None, None, None, :, :])
                 + g(y1i, x1i) * (wy1[None, :, :, None, None]
                                  * wx1[None, None, None, :, :]))
            ok = (oky[None, :, :, None, None]
                  & okx[None, None, None, :, :])
            return jnp.where(ok, v, 0.0)

        def per_roi(bi, yy, xx, vy, vx, ny, nx):
            img = feat[bi]
            v = bilinear(img, yy, xx)               # [C,ph,Gy,pw,Gx]
            w = (vy[None, None, :, None, None]
                 & vx[None, None, None, None, :])
            return jnp.sum(jnp.where(w, v, 0.0),
                           axis=(2, 4)) / (ny * nx)  # [C,ph,pw]
        return jax.vmap(per_roi)(bidx, ys, xs, my, mx, gy, gx)
    return apply("roi_align", impl, input, rois)


def roi_pool(input, rois, output_size, spatial_scale=1.0, rois_num=None,
             name=None):
    """reference: operators/roi_pool_op.cc (max pool per bin, integer
    quantized boundaries)."""
    if isinstance(output_size, int):
        ph = pw = int(output_size)
    else:
        ph, pw = output_size
    if rois_num is not None:
        rn = np.asarray(rois_num._data if isinstance(rois_num, Tensor)
                        else rois_num)
        batch_of = np.repeat(np.arange(rn.shape[0]), rn).astype(np.int32)
    else:
        batch_of = None

    def impl(feat, boxes):
        N, C, H, W = feat.shape
        R = boxes.shape[0]
        bidx = (jnp.asarray(batch_of) if batch_of is not None
                else jnp.zeros((R,), jnp.int32))
        b = jnp.round(boxes * spatial_scale)
        x1 = jnp.clip(b[:, 0], 0, W - 1).astype(jnp.int32)
        y1 = jnp.clip(b[:, 1], 0, H - 1).astype(jnp.int32)
        x2 = jnp.clip(b[:, 2], 0, W - 1).astype(jnp.int32)
        y2 = jnp.clip(b[:, 3], 0, H - 1).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)

        yy = jnp.arange(H)
        xx = jnp.arange(W)

        def per_roi(args):
            bi, xx1, yy1, hh, ww = args
            img = feat[bi]                           # [C,H,W]
            # bin id of every pixel (or -1 outside the roi)
            py = ((yy - yy1) * ph) // hh
            px = ((xx - xx1) * pw) // ww
            py = jnp.where((yy >= yy1) & (yy < yy1 + hh), py, -1)
            px = jnp.where((xx >= xx1) & (xx < xx1 + ww), px, -1)
            onehot_y = (py[None, :] == jnp.arange(ph)[:, None])  # [ph,H]
            onehot_x = (px[None, :] == jnp.arange(pw)[:, None])  # [pw,W]
            # two-step windowed max keeps the peak intermediate at
            # [C,H,pw] instead of a dense [C,ph,H,pw,W] product
            mx = jnp.where(onehot_x[None, None, :, :],
                           img[:, :, None, :], -jnp.inf).max(axis=3)
            out = jnp.where(onehot_y[None, :, :, None],
                            mx[:, None, :, :], -jnp.inf).max(axis=2)
            return jnp.where(jnp.isfinite(out), out, 0.0)   # [C,ph,pw]
        # lax.map serializes ROIs: peak memory is ONE roi's intermediate
        return jax.lax.map(per_roi, (bidx, x1, y1, rh, rw))
    return apply("roi_pool", impl, input, rois)


# -- rpn / fpn ----------------------------------------------------------------

def anchor_generator(input, anchor_sizes, aspect_ratios, variances,
                     stride, offset=0.5, name=None):
    """reference: detection/anchor_generator_op.cc — grid anchors
    [H, W, A, 4] + variances broadcast."""
    sizes = [float(s) for s in np.atleast_1d(anchor_sizes)]
    ratios = [float(r) for r in np.atleast_1d(aspect_ratios)]
    var = np.asarray(variances, np.float32)
    sx, sy = (stride if isinstance(stride, (list, tuple))
              else (stride, stride))

    def impl(x):
        H, W = x.shape[2], x.shape[3]
        cx = (jnp.arange(W) + offset) * sx
        cy = (jnp.arange(H) + offset) * sy
        ws, hs = [], []
        for r in ratios:
            for s in sizes:
                ws.append(s * np.sqrt(1.0 / r))
                hs.append(s * np.sqrt(r))
        ws = jnp.asarray(ws, jnp.float32)
        hs = jnp.asarray(hs, jnp.float32)
        boxes = jnp.stack([
            cx[None, :, None] - 0.5 * ws[None, None, :]
            + 0 * cy[:, None, None],
            cy[:, None, None] - 0.5 * hs[None, None, :]
            + 0 * cx[None, :, None],
            cx[None, :, None] + 0.5 * ws[None, None, :]
            + 0 * cy[:, None, None],
            cy[:, None, None] + 0.5 * hs[None, None, :]
            + 0 * cx[None, :, None],
        ], axis=-1)                                   # [H, W, A, 4]
        v = jnp.broadcast_to(jnp.asarray(var), boxes.shape)
        return boxes, v
    return apply("anchor_generator", impl, input)


def generate_proposals(scores, bbox_deltas, im_shape, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True, name=None):
    """reference: detection/generate_proposals_op.cc (RPN): decode deltas
    against anchors, clip, filter small boxes, top-k, NMS. Fixed-size
    masked outputs: rois [N, post_nms_top_n, 4], scores [N, post_nms_top_n],
    rois_num [N]."""
    off = 1.0 if pixel_offset else 0.0

    def impl(sc, deltas, imshape, anc, var):
        N = sc.shape[0]
        A = anc.reshape(-1, 4).shape[0]
        anc_f = anc.reshape(-1, 4)
        var_f = var.reshape(-1, 4)

        def per_image(s, d, ish):
            # scores [A,H,W] / deltas [4A,H,W] flatten in (H,W,A) order to
            # line up with anchor_generator's [H,W,A,4] layout (reference
            # transposes with axis{0,2,3,1} the same way)
            if s.ndim == 3:  # noqa: PTA008 -- rank dispatch between the reference's two documented score layouts; rank is fixed per op signature, not per batch
                s_f = jnp.transpose(s, (1, 2, 0)).reshape(-1)
            else:
                s_f = s.reshape(-1)
            if d.ndim == 3:  # noqa: PTA008 -- same two-layout rank dispatch for deltas; both forms are traced deliberately
                d_r = d.reshape(-1, 4, d.shape[-2], d.shape[-1])
                d_f = jnp.transpose(d_r, (2, 3, 0, 1)).reshape(-1, 4)
            else:
                d_f = d.reshape(-1, 4)
            # decode (box_coder decode_center_size semantics)
            aw = anc_f[:, 2] - anc_f[:, 0] + off
            ah = anc_f[:, 3] - anc_f[:, 1] + off
            acx = anc_f[:, 0] + 0.5 * aw
            acy = anc_f[:, 1] + 0.5 * ah
            cx = var_f[:, 0] * d_f[:, 0] * aw + acx
            cy = var_f[:, 1] * d_f[:, 1] * ah + acy
            w = jnp.exp(jnp.minimum(var_f[:, 2] * d_f[:, 2], 10.0)) * aw
            h = jnp.exp(jnp.minimum(var_f[:, 3] * d_f[:, 3], 10.0)) * ah
            boxes = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                               cx + 0.5 * w - off, cy + 0.5 * h - off],
                              axis=1)
            # clip to image
            hgt, wid = ish[0], ish[1]
            boxes = jnp.stack([
                jnp.clip(boxes[:, 0], 0, wid - off),
                jnp.clip(boxes[:, 1], 0, hgt - off),
                jnp.clip(boxes[:, 2], 0, wid - off),
                jnp.clip(boxes[:, 3], 0, hgt - off)], axis=1)
            ww = boxes[:, 2] - boxes[:, 0] + off
            hh = boxes[:, 3] - boxes[:, 1] + off
            ok = (ww >= min_size) & (hh >= min_size)
            s_m = jnp.where(ok, s_f, -jnp.inf)
            k = min(int(pre_nms_top_n), s_m.shape[0])
            top_s, top_i = lax.top_k(s_m, k)
            cand = boxes[top_i]
            kept, order, kept_s = _greedy_nms_mask(
                cand, top_s, nms_thresh, -jnp.inf, k,
                normalized=not pixel_offset, nms_eta=eta)
            sel_sc = jnp.where(kept & jnp.isfinite(kept_s), kept_s, -jnp.inf)
            kk = min(int(post_nms_top_n), sel_sc.shape[0])
            fin_s, fin_i = lax.top_k(sel_sc, kk)
            fin_boxes = cand[order][fin_i]
            valid = jnp.isfinite(fin_s)
            out_boxes = jnp.where(valid[:, None], fin_boxes, 0.0)
            out_s = jnp.where(valid, fin_s, 0.0)
            if kk < post_nms_top_n:
                padb = jnp.zeros((post_nms_top_n - kk, 4), out_boxes.dtype)
                out_boxes = jnp.concatenate([out_boxes, padb], 0)
                out_s = jnp.concatenate(
                    [out_s, jnp.zeros(post_nms_top_n - kk, out_s.dtype)], 0)
                valid = jnp.concatenate(
                    [valid, jnp.zeros(post_nms_top_n - kk, bool)], 0)
            return out_boxes, out_s, valid.sum().astype(jnp.int32)
        rois, rsc, rn = jax.vmap(per_image)(sc, deltas, imshape)
        return rois, rsc, rn
    return apply("generate_proposals", impl, scores, bbox_deltas, im_shape,
                 anchors, variances)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, pixel_offset=False,
                             name=None):
    """reference: detection/distribute_fpn_proposals_op.cc — route each roi
    to the FPN level matching its scale. Masked fixed-size outputs: one
    [R, 4] tensor + validity mask per level, plus restore_index [R]."""
    off = 1.0 if pixel_offset else 0.0
    levels = list(range(int(min_level), int(max_level) + 1))

    def impl(rois):
        w = rois[:, 2] - rois[:, 0] + off
        h = rois[:, 3] - rois[:, 1] + off
        scale = jnp.sqrt(jnp.maximum(w * h, 1e-12))
        lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + refer_level
        lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
        outs = []
        masks = []
        for L in levels:
            m = lvl == L
            outs.append(jnp.where(m[:, None], rois, 0.0))
            masks.append(m)
        # restore_index: position of each original roi in the level-major
        # concatenation (reference returns the inverse permutation)
        order_key = lvl * rois.shape[0] + jnp.arange(rois.shape[0])
        perm = jnp.argsort(order_key)
        restore = jnp.argsort(perm).astype(jnp.int32)
        return tuple(outs) + tuple(masks) + (restore,)
    flat = apply("distribute_fpn_proposals", impl, fpn_rois)
    n = len(levels)
    return list(flat[:n]), list(flat[n:2 * n]), flat[2 * n]


def collect_fpn_proposals(multi_rois, multi_scores, post_nms_top_n,
                          rois_num_per_level=None, name=None):
    """reference: detection/collect_fpn_proposals_op.cc — merge per-level
    rois, keep global top-k by score."""
    k = int(post_nms_top_n)

    def impl(*args):
        n = len(args) // 2
        rois = jnp.concatenate(args[:n], axis=0)
        scores = jnp.concatenate([a.reshape(-1) for a in args[n:]], axis=0)
        kk = min(k, scores.shape[0])
        top_s, top_i = lax.top_k(scores, kk)
        return rois[top_i], top_s
    return apply("collect_fpn_proposals", impl,
                 *(list(multi_rois) + list(multi_scores)))


# -- matching / assignment ----------------------------------------------------

def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """reference: detection/bipartite_match_op.cc — greedy bipartite
    matching on a [M, N] distance (similarity) matrix: repeatedly take the
    globally largest entry, retire its row+column. Returns
    (match_indices [1, N] int, match_dist [1, N])."""
    def impl(dm):
        M, N = dm.shape[-2], dm.shape[-1]
        steps = min(M, N)

        def step(carry, _):
            mat, row_ok, col_ok = carry
            masked = jnp.where(row_ok[:, None] & col_ok[None, :], mat,
                               -jnp.inf)
            flat = masked.reshape(-1)
            best = jnp.argmax(flat)
            r, c = best // N, best % N
            good = flat[best] > -jnp.inf
            row_ok = row_ok.at[r].set(jnp.where(good, False, row_ok[r]))
            col_ok = col_ok.at[c].set(jnp.where(good, False, col_ok[c]))
            return (mat, row_ok, col_ok), (r, c, flat[best], good)

        (_, _, _), (rs, cs, vs, goods) = lax.scan(
            step, (dm, jnp.ones(M, bool), jnp.ones(N, bool)),
            jnp.arange(steps))
        match = jnp.full((N,), -1, jnp.int32)
        mdist = jnp.zeros((N,), dm.dtype)
        # bad steps (all remaining pairs masked/-inf) must not scatter at
        # all — route them to an out-of-range index with drop mode, else
        # duplicate writes at column 0 clobber a real match
        cs_ok = jnp.where(goods, cs, N)
        match = match.at[cs_ok].set(rs.astype(jnp.int32), mode="drop")
        mdist = mdist.at[cs_ok].set(vs, mode="drop")
        if match_type == "per_prediction" and dist_threshold is not None:
            # additionally match every unmatched column to its best row if
            # above threshold (reference match_type='per_prediction')
            best_r = jnp.argmax(dm, axis=0).astype(jnp.int32)
            best_v = jnp.max(dm, axis=0)
            extra = (match < 0) & (best_v >= dist_threshold)
            match = jnp.where(extra, best_r, match)
            mdist = jnp.where(extra, best_v, mdist)
        return match[None, :], mdist[None, :]
    return apply("bipartite_match", impl, dist_matrix)


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """reference: detection/target_assign_op.cc — out[i][j] =
    input[matched_indices[i][j]] (mismatch -> mismatch_value);
    weights 1 for matched, 0 otherwise."""
    def impl(x, mi):
        def per_row(m):
            ok = m >= 0
            g = x[jnp.clip(m, 0, x.shape[0] - 1)]
            out = jnp.where(ok[..., None] if g.ndim > m.ndim else ok, g,
                            jnp.asarray(mismatch_value, g.dtype))
            w = ok.astype(jnp.float32)
            return out, w
        return jax.vmap(per_row)(mi)
    return apply("target_assign", impl, input, matched_indices)


def box_decoder_and_assign(prior_box_t, prior_box_var, target_box,
                           box_score, box_clip=4.135, name=None):
    """reference: detection/box_decoder_and_assign_op.cc — decode per-class
    deltas then pick each box's best-scoring class decode."""
    def impl(pb, pbv, tb, sc):
        n = pb.shape[0]
        c4 = tb.shape[1]
        ncls = c4 // 4
        pw = pb[:, 2] - pb[:, 0] + 1.0
        phh = pb[:, 3] - pb[:, 1] + 1.0
        pcx = pb[:, 0] + 0.5 * pw
        pcy = pb[:, 1] + 0.5 * phh
        d = tb.reshape(n, ncls, 4)
        dx = d[..., 0] * pbv[:, None, 0]
        dy = d[..., 1] * pbv[:, None, 1]
        dw = jnp.clip(d[..., 2] * pbv[:, None, 2], None, box_clip)
        dh = jnp.clip(d[..., 3] * pbv[:, None, 3], None, box_clip)
        cx = dx * pw[:, None] + pcx[:, None]
        cy = dy * phh[:, None] + pcy[:, None]
        w = jnp.exp(dw) * pw[:, None]
        h = jnp.exp(dh) * phh[:, None]
        decoded = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                             cx + 0.5 * w - 1, cy + 0.5 * h - 1], axis=-1)
        best = jnp.argmax(sc[:, 1:], axis=1) + 1  # skip background col 0
        assigned = jnp.take_along_axis(
            decoded, best[:, None, None].repeat(4, -1), axis=1)[:, 0]
        return decoded.reshape(n, c4), assigned
    return apply("box_decoder_and_assign", impl, prior_box_t, prior_box_var,
                 target_box, box_score)


def polygon_box_transform(input, name=None):
    """reference: detection/polygon_box_transform_op.cc — offset-map to
    absolute quad coords: out = 4*stride_grid + in (even channels x,
    odd y)."""
    def impl(x):
        N, C, H, W = x.shape
        gx = jnp.broadcast_to(jnp.arange(W)[None, :] * 4.0, (H, W))
        gy = jnp.broadcast_to(jnp.arange(H)[:, None] * 4.0, (H, W))
        grid = jnp.where((jnp.arange(C) % 2 == 0)[None, :, None, None],
                         gx[None, None], gy[None, None])
        return grid + x
    return apply("polygon_box_transform", impl, input)


# -- losses / misc ------------------------------------------------------------

def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0,
              name=None):
    """reference: operators/smooth_l1_loss_op.cc — per-row summed huberized
    loss with inside/outside weights."""
    s2 = float(sigma) * float(sigma)

    def impl(a, b, *ws):
        it = iter(ws)
        iw = next(it) if inside_weight is not None else None
        ow = next(it) if outside_weight is not None else None
        d = a - b
        if iw is not None:
            d = d * iw
        ad = jnp.abs(d)
        val = jnp.where(ad < 1.0 / s2, 0.5 * d * d * s2, ad - 0.5 / s2)
        if ow is not None:
            val = val * ow
        return val.reshape(a.shape[0], -1).sum(axis=1, keepdims=True)
    args = [x, y]
    if inside_weight is not None:
        args.append(inside_weight)
    if outside_weight is not None:
        args.append(outside_weight)
    return apply("smooth_l1", impl, *args)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """reference: detection/matrix_nms_op.cc — parallel soft-NMS: each
    box's score is decayed by min_j f(iou_ij)/f(max_iou_j) over
    higher-scored boxes j (no sequential suppression loop — MXU friendly).
    Fixed-size output like multiclass_nms: out [N, keep_top_k, 6],
    index [N, keep_top_k], counts [N]."""
    def decay_fn(iou, comp):
        if use_gaussian:
            return jnp.exp((comp * comp - iou * iou) / gaussian_sigma)
        return (1.0 - iou) / (1.0 - comp)

    def impl(bb, sc):
        n, c, m = sc.shape

        def per_image(boxes, cls_scores):
            outs = []
            for cls in range(c):
                if cls == background_label:
                    continue
                s = cls_scores[cls]
                k = min(int(nms_top_k), m)
                top_s, order = lax.top_k(s, k)
                cand = boxes[order]
                iou = _pairwise_iou(cand, cand, normalized=normalized)
                idx = jnp.arange(k)
                before = idx[:, None] < idx[None, :]    # [j, i]: j ranks
                # above i — j is a potential suppressor of i
                iou_ji = jnp.where(before, iou, 0.0)
                # compensation: each suppressor j's own max overlap with
                # anything ranked above IT (matrix_nms_op.cc decay/comp)
                comp = jnp.max(jnp.where(before.T, iou, 0.0), axis=1)  # [j]
                factor = decay_fn(iou_ji, comp[:, None])
                factor = jnp.where(before, factor, 1.0)
                dec = jnp.min(factor, axis=0)           # per i over all j
                ds = jnp.where(top_s > score_threshold, top_s * dec, -1.0)
                ds = jnp.where(ds > post_threshold, ds, -1.0)
                outs.append((jnp.full_like(ds, cls), ds, cand, order))
            labels = jnp.concatenate([o[0] for o in outs])
            dscores = jnp.concatenate([o[1] for o in outs])
            cboxes = jnp.concatenate([o[2] for o in outs], axis=0)
            kk = min(int(keep_top_k), dscores.shape[0])
            best, idx = lax.top_k(dscores, kk)
            valid = best >= 0
            row = jnp.concatenate([
                jnp.where(valid, labels[idx], -1.0)[:, None],
                jnp.where(valid, best, 0.0)[:, None],
                jnp.where(valid[:, None], cboxes[idx], 0.0)], axis=1)
            if kk < keep_top_k:
                pad = jnp.zeros((keep_top_k - kk, 6), row.dtype)
                pad = pad.at[:, 0].set(-1.0)
                row = jnp.concatenate([row, pad], axis=0)
                idx = jnp.concatenate(
                    [idx, jnp.zeros(keep_top_k - kk, idx.dtype)])
                valid = jnp.concatenate(
                    [valid, jnp.zeros(keep_top_k - kk, bool)])
            return row, idx.astype(jnp.int32), valid.sum().astype(jnp.int32)
        outs, idxs, counts = jax.vmap(per_image)(bb, sc)
        return outs, idxs, counts
    out, idx, counts = apply("matrix_nms", impl, bboxes, scores)
    if return_index:
        return out, idx, counts
    return out, counts


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    """reference: detection/density_prior_box_op.cc (SSD densified
    anchors)."""
    var = np.asarray(variance, np.float32)

    def impl(x, img):
        H, W = x.shape[2], x.shape[3]
        IH, IW = img.shape[2], img.shape[3]
        sx = steps[0] or IW / W
        sy = steps[1] or IH / H
        boxes_per_loc = []
        for density, fs in zip(densities, fixed_sizes):
            for fr in fixed_ratios:
                bw = fs * np.sqrt(fr)
                bh = fs / np.sqrt(fr)
                shift = fs / density
                for di in range(density):
                    for dj in range(density):
                        ox = (-fs / 2.0 + shift / 2.0 + dj * shift)
                        oy = (-fs / 2.0 + shift / 2.0 + di * shift)
                        boxes_per_loc.append((ox, oy, bw, bh))
        A = len(boxes_per_loc)
        cx = (jnp.arange(W) + offset) * sx
        cy = (jnp.arange(H) + offset) * sy
        params = jnp.asarray(boxes_per_loc, jnp.float32)  # [A,4]
        bx = cx[None, :, None] + params[None, None, :, 0] \
            + 0 * cy[:, None, None]
        by = cy[:, None, None] + params[None, None, :, 1] \
            + 0 * cx[None, :, None]
        bw = params[None, None, :, 2]
        bh = params[None, None, :, 3]
        out = jnp.stack([
            (bx - bw / 2) / IW, (by - bh / 2) / IH,
            (bx + bw / 2) / IW, (by + bh / 2) / IH], axis=-1)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        v = jnp.broadcast_to(jnp.asarray(var), out.shape)
        if flatten_to_2d:
            return out.reshape(-1, 4), v.reshape(-1, 4)
        return out, v
    return apply("density_prior_box", impl, input, image)


# -- position-sensitive ROI pooling ------------------------------------------

def _rois_batch_index(rois_num, R):
    if rois_num is None:
        return None
    rn = np.asarray(rois_num._data if isinstance(rois_num, Tensor)
                    else rois_num)
    return np.repeat(np.arange(rn.shape[0]), rn).astype(np.int32)


def psroi_pool(x, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_num=None, name=None):
    """reference: operators/psroi_pool_op.cc:79 (CPUPSROIPoolOpKernel).

    Position-sensitive ROI average pooling (R-FCN): input [N, C, H, W] with
    C = output_channels * ph * pw; bin (i, j) of output channel c averages
    input channel (c*ph + i)*pw + j over the bin region. The reference
    walks each bin's pixels; bin edges are integer (floor/ceil of scaled
    roi coords), so a summed-area table gives the same sums with static
    shapes and one cumsum pass — no per-bin loops.

    Output [R, output_channels, ph, pw]; empty bins are 0 (reference
    ``is_empty`` branch).
    """
    ph, pw = int(pooled_height), int(pooled_width)
    oc = int(output_channels)
    batch_of = _rois_batch_index(rois_num, None)

    def impl(feat, boxes):
        N, C, H, W = feat.shape
        R = boxes.shape[0]
        bidx = (jnp.asarray(batch_of) if batch_of is not None
                else jnp.zeros((R,), jnp.int32))
        # reference rounds the raw roi coords, then scales
        x1 = jnp.round(boxes[:, 0]) * spatial_scale
        y1 = jnp.round(boxes[:, 1]) * spatial_scale
        x2 = jnp.round(boxes[:, 2] + 1.0) * spatial_scale
        y2 = jnp.round(boxes[:, 3] + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h = rh / ph
        bin_w = rw / pw
        iy = jnp.arange(ph, dtype=feat.dtype)
        ix = jnp.arange(pw, dtype=feat.dtype)
        hs = jnp.clip(jnp.floor(iy[None, :] * bin_h[:, None] + y1[:, None]),
                      0, H).astype(jnp.int32)                  # [R, ph]
        he = jnp.clip(jnp.ceil((iy[None, :] + 1) * bin_h[:, None]
                               + y1[:, None]), 0, H).astype(jnp.int32)
        ws = jnp.clip(jnp.floor(ix[None, :] * bin_w[:, None] + x1[:, None]),
                      0, W).astype(jnp.int32)                  # [R, pw]
        we = jnp.clip(jnp.ceil((ix[None, :] + 1) * bin_w[:, None]
                               + x1[:, None]), 0, W).astype(jnp.int32)
        # summed-area table, zero-padded leading edge: [N, C, H+1, W+1]
        sat = jnp.pad(jnp.cumsum(jnp.cumsum(
            feat.astype(jnp.float32), axis=2), axis=3),
            ((0, 0), (0, 0), (1, 0), (1, 0)))
        sat_r = sat[bidx]                                       # [R,C,H1,W1]
        cin = ((jnp.arange(oc)[:, None, None] * ph
                + jnp.arange(ph)[None, :, None]) * pw
               + jnp.arange(pw)[None, None, :])                 # [oc,ph,pw]
        r_i = jnp.arange(R)[:, None, None, None]
        c_i = cin[None]
        h0 = hs[:, None, :, None]
        h1 = he[:, None, :, None]
        w0 = ws[:, None, None, :]
        w1 = we[:, None, None, :]
        s = (sat_r[r_i, c_i, h1, w1] - sat_r[r_i, c_i, h0, w1]
             - sat_r[r_i, c_i, h1, w0] + sat_r[r_i, c_i, h0, w0])
        count = ((he - hs)[:, None, :, None]
                 * (we - ws)[:, None, None, :]).astype(jnp.float32)
        out = jnp.where(count > 0, s / jnp.maximum(count, 1.0), 0.0)
        return out.astype(feat.dtype)
    return apply("psroi_pool", impl, x, rois)


def _tri_integral(t):
    """Antiderivative of the triangle kernel max(0, 1-|s|) evaluated at t:
    g(t) = integral_{-1}^{t} max(0, 1-|s|) ds (piecewise quadratic)."""
    t = jnp.clip(t, -1.0, 1.0)
    return jnp.where(t <= 0, 0.5 * (t + 1.0) ** 2,
                     0.5 + t * (1.0 - 0.5 * t))


def prroi_pool(x, rois, pooled_height, pooled_width, spatial_scale=1.0,
               rois_num=None, name=None):
    """reference: operators/prroi_pool_op.cc (Precise RoI Pooling, no
    quantization: the bin average is the exact integral of the bilinearly
    interpolated feature over the continuous bin).

    The bilinear surface is separable, so the integral factors into 1-D
    triangle-kernel integrals per axis:

        out[r,c,i,j] = (1/area) * sum_{h,w} feat[c,h,w] * Ih[r,i,h] * Iw[r,j,w]

    with Ih/Iw closed-form (quadratic) antiderivative differences — the
    whole op becomes two dense contractions, which XLA maps onto the MXU
    (the reference GPU kernel instead walks pixels with atomicAdd).
    """
    ph, pw = int(pooled_height), int(pooled_width)
    batch_of = _rois_batch_index(rois_num, None)

    def impl(feat, boxes):
        N, C, H, W = feat.shape
        R = boxes.shape[0]
        bidx = (jnp.asarray(batch_of) if batch_of is not None
                else jnp.zeros((R,), jnp.int32))
        b = boxes.astype(jnp.float32) * spatial_scale
        x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
        bin_h = (y2 - y1) / ph                                  # [R]
        bin_w = (x2 - x1) / pw
        iy = jnp.arange(ph, dtype=jnp.float32)
        ix = jnp.arange(pw, dtype=jnp.float32)
        hs = y1[:, None] + iy[None, :] * bin_h[:, None]         # [R, ph]
        he = hs + bin_h[:, None]
        ws = x1[:, None] + ix[None, :] * bin_w[:, None]         # [R, pw]
        we = ws + bin_w[:, None]
        hh = jnp.arange(H, dtype=jnp.float32)
        wws = jnp.arange(W, dtype=jnp.float32)
        # weight of pixel h for bin i = g(he - h) - g(hs - h)
        Ih = (_tri_integral(he[:, :, None] - hh[None, None, :])
              - _tri_integral(hs[:, :, None] - hh[None, None, :]))  # [R,ph,H]
        Iw = (_tri_integral(we[:, :, None] - wws[None, None, :])
              - _tri_integral(ws[:, :, None] - wws[None, None, :]))  # [R,pw,W]
        fr = feat.astype(jnp.float32)[bidx]                     # [R,C,H,W]
        out = jnp.einsum("rchw,rih,rjw->rcij", fr, Ih, Iw)
        area = jnp.maximum(bin_h[:, None, None, None]
                           * bin_w[:, None, None, None], 1e-9)
        return (out / area).astype(feat.dtype)
    return apply("prroi_pool", impl, x, rois)


def deformable_psroi_pooling(x, rois, trans, no_trans=False,
                             spatial_scale=1.0, group_size=1,
                             pooled_height=1, pooled_width=1, part_size=1,
                             sample_per_part=4, trans_std=0.1,
                             rois_num=None, name=None):
    """reference: operators/deformable_psroi_pooling_op.cc
    (DeformablePSROIPoolForwardCPUKernel): position-sensitive ROI pooling
    with learned per-part offsets (Deformable R-FCN). Each bin is shifted
    by ``trans[r, :, part_i, part_j] * trans_std * roi_extent`` then
    averaged over a fixed ``sample_per_part`` x ``sample_per_part`` grid of
    bilinear taps — the tap grid is static, so the op is one fused gather.

    x [N, C, H, W] with C = oc * gs * gs; trans [R, 2, part, part]
    (ignored when no_trans). Output [R, oc, ph, pw].
    """
    ph, pw = int(pooled_height), int(pooled_width)
    gs, sp = int(group_size), int(sample_per_part)
    pt = int(part_size)
    batch_of = _rois_batch_index(rois_num, None)

    def impl(feat, boxes, tr):
        N, C, H, W = feat.shape
        oc = C // (gs * gs)
        R = boxes.shape[0]
        bidx = (jnp.asarray(batch_of) if batch_of is not None
                else jnp.zeros((R,), jnp.int32))
        b = boxes.astype(jnp.float32)
        # reference: round + 0.5-offset roi corners, min extent 0.1
        x1 = jnp.round(b[:, 0]) * spatial_scale - 0.5
        y1 = jnp.round(b[:, 1]) * spatial_scale - 0.5
        x2 = (jnp.round(b[:, 2]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(b[:, 3]) + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h = rh / ph                                          # [R]
        bin_w = rw / pw
        sub_h = bin_h / sp
        sub_w = bin_w / sp
        iy = jnp.arange(ph)
        ix = jnp.arange(pw)
        # part index of each bin (part grid may be coarser than the output)
        py = jnp.clip((iy * pt) // ph, 0, pt - 1)                # [ph]
        px = jnp.clip((ix * pt) // pw, 0, pt - 1)                # [pw]
        if no_trans:
            dy = jnp.zeros((R, ph, pw), jnp.float32)
            dx = jnp.zeros((R, ph, pw), jnp.float32)
        else:
            cls = 0  # single offset class (reference: num_classes from trans)
            # offset of bin (i, j) comes from its (part_i, part_j) cell
            dy = tr[:, 2 * cls][:, py][:, :, px] * trans_std * rh[:, None, None]
            dx = tr[:, 2 * cls + 1][:, py][:, :, px] * trans_std * rw[:, None, None]
        s = jnp.arange(sp, dtype=jnp.float32)
        # tap coords [R, ph(pw), sp]
        ty = (y1[:, None] + iy[None, :] * bin_h[:, None])[:, :, None] \
            + (s[None, None, :] + 0.5) * sub_h[:, None, None]
        tx = (x1[:, None] + ix[None, :] * bin_w[:, None])[:, :, None] \
            + (s[None, None, :] + 0.5) * sub_w[:, None, None]
        ty = ty[:, :, None, :, None] + dy[:, :, :, None, None]   # [R,ph,pw,sp,1]
        tx = tx[:, None, :, None, :] + dx[:, :, :, None, None]   # [R,ph,pw,1,sp]
        ty = jnp.broadcast_to(ty, (R, ph, pw, sp, sp))
        tx = jnp.broadcast_to(tx, (R, ph, pw, sp, sp))
        # reference skips taps outside [-0.5, extent-0.5]
        inside = ((ty >= -0.5) & (ty <= H - 0.5)
                  & (tx >= -0.5) & (tx <= W - 0.5))
        ty = jnp.clip(ty, 0.0, H - 1.0)
        tx = jnp.clip(tx, 0.0, W - 1.0)
        y0 = jnp.floor(ty).astype(jnp.int32)
        x0 = jnp.floor(tx).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, H - 1)
        x1i = jnp.minimum(x0 + 1, W - 1)
        ay = ty - y0
        ax = tx - x0
        # position-sensitive input channel per (c, group bin)
        gy = jnp.clip((iy * gs) // ph, 0, gs - 1)                # [ph]
        gx = jnp.clip((ix * gs) // pw, 0, gs - 1)                # [pw]
        cin = (jnp.arange(oc)[:, None, None] * gs
               + gy[None, :, None]) * gs + gx[None, None, :]     # [oc,ph,pw]
        fr = feat[bidx]                                          # [R,C,H,W]
        r_i = jnp.arange(R)[:, None, None, None, None, None]
        c_i = cin[None, :, :, :, None, None]
        yA = y0[:, None]; yB = y1i[:, None]
        xA = x0[:, None]; xB = x1i[:, None]
        wA = ((1 - ay) * (1 - ax))[:, None]
        wB = ((1 - ay) * ax)[:, None]
        wC = (ay * (1 - ax))[:, None]
        wD = (ay * ax)[:, None]
        val = (fr[r_i, c_i, yA, xA] * wA + fr[r_i, c_i, yA, xB] * wB
               + fr[r_i, c_i, yB, xA] * wC + fr[r_i, c_i, yB, xB] * wD)
        m = inside[:, None].astype(val.dtype)
        cnt = jnp.maximum(jnp.sum(m, axis=(-1, -2)), 1.0)
        out = jnp.sum(val * m, axis=(-1, -2)) / cnt
        return out.astype(feat.dtype)
    if no_trans and trans is None:
        trans = np.zeros((1, 2, pt, pt), np.float32)
    return apply("deformable_psroi_pooling", impl, x, rois, trans)
