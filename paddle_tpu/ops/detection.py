"""Detection ops: yolo_box / yolov3_loss / multiclass_nms / prior_box /
box_coder / iou_similarity / box_clip.

TPU-native equivalents of the reference detection op family
(reference: paddle/fluid/operators/detection/yolo_box_op.cc,
yolov3_loss_op.cc, multiclass_nms_op.cc, prior_box_op.cc, box_coder_op.cc,
iou_similarity_op.cc, box_clip_op.cc).

Dynamic-shape strategy (SURVEY §7 hard part; the reference emits LoD
tensors of ragged size): every op here has a FIXED-size output with an
explicit validity convention —
- ground-truth boxes arrive padded to a constant slot count, zero-area
  slots are ignored;
- multiclass_nms returns exactly ``keep_top_k`` rows per image, invalid
  rows carry label -1 (callers mask on label >= 0) plus an explicit count.
This keeps one compiled XLA program per shape bucket instead of per input.
All ops are pure jnp/lax compositions — XLA fuses them; none needed a
Pallas kernel at the measured sizes (SURVEY App. C item 4 candidates).
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .dispatch import apply

__all__ = ["yolo_box", "yolov3_loss", "multiclass_nms", "prior_box",
           "box_coder", "iou_similarity", "box_clip"]


def _sigmoid(x):
    return jax.nn.sigmoid(x)


# -- yolo_box -----------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    """reference: detection/yolo_box_op.cc (GetYoloBox/CalcDetectionBox).

    x: [N, A*(5+C), H, W]; img_size: [N, 2] (h, w).
    Returns boxes [N, A*H*W, 4] (x1y1x2y2 in image scale) and scores
    [N, A*H*W, C]; boxes with conf < conf_thresh are zeroed.
    """
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = anchors.shape[0]
    C = int(class_num)

    def impl(xr, img):
        n, _, h, w = xr.shape
        p = xr.reshape(n, A, 5 + C, h, w)
        grid_x = jnp.arange(w, dtype=xr.dtype).reshape(1, 1, 1, w)
        grid_y = jnp.arange(h, dtype=xr.dtype).reshape(1, 1, h, 1)
        alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
        bx = (_sigmoid(p[:, :, 0]) * alpha + beta + grid_x) / w
        by = (_sigmoid(p[:, :, 1]) * alpha + beta + grid_y) / h
        input_h = h * downsample_ratio
        input_w = w * downsample_ratio
        an_w = (anchors[:, 0] / input_w).reshape(1, A, 1, 1).astype(xr.dtype)
        an_h = (anchors[:, 1] / input_h).reshape(1, A, 1, 1).astype(xr.dtype)
        bw = jnp.exp(p[:, :, 2]) * an_w
        bh = jnp.exp(p[:, :, 3]) * an_h
        conf = _sigmoid(p[:, :, 4])
        keep = conf >= conf_thresh
        img_h = img[:, 0].astype(xr.dtype).reshape(n, 1, 1, 1)
        img_w = img[:, 1].astype(xr.dtype).reshape(n, 1, 1, 1)
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
            x2 = jnp.clip(x2, 0, img_w - 1)
            y2 = jnp.clip(y2, 0, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
        boxes = jnp.where(keep[..., None], boxes, 0.0)
        scores = conf[..., None] * _sigmoid(
            jnp.moveaxis(p[:, :, 5:], 2, -1))
        scores = jnp.where(keep[..., None], scores, 0.0)
        # [N, A, H, W, k] -> [N, A*H*W, k]
        return (boxes.reshape(n, A * h * w, 4),
                scores.reshape(n, A * h * w, C))
    return apply("yolo_box", impl, x, img_size)


# -- iou helpers --------------------------------------------------------------

def _pairwise_iou(a, b, normalized=True):
    """a [M,4], b [K,4] x1y1x2y2 -> [M,K]. Unnormalized (pixel) boxes get
    the reference's +1 extent offset (JaccardOverlap, detection/nms_util.h)."""
    off = 0.0 if normalized else 1.0
    area_a = jnp.clip(a[:, 2] - a[:, 0] + off, 0, None) * \
        jnp.clip(a[:, 3] - a[:, 1] + off, 0, None)
    area_b = jnp.clip(b[:, 2] - b[:, 0] + off, 0, None) * \
        jnp.clip(b[:, 3] - b[:, 1] + off, 0, None)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt + off, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def iou_similarity(x, y, box_normalized=True, name=None):
    """reference: detection/iou_similarity_op.cc — [M,4]x[K,4] -> [M,K]."""
    return apply("iou_similarity",
                 lambda a, b: _pairwise_iou(a, b, normalized=box_normalized),
                 x, y)


def box_clip(input, im_info, name=None):
    """reference: detection/box_clip_op.cc — clip to [0, dim-1]."""
    def impl(boxes, info):
        h, w = info[0], info[1]
        return jnp.stack([
            jnp.clip(boxes[..., 0], 0, w - 1),
            jnp.clip(boxes[..., 1], 0, h - 1),
            jnp.clip(boxes[..., 2], 0, w - 1),
            jnp.clip(boxes[..., 3], 0, h - 1)], axis=-1)
    return apply("box_clip", impl, input, im_info)


# -- multiclass_nms -----------------------------------------------------------

def _greedy_nms_mask(boxes, scores, iou_threshold, score_threshold, top_k,
                     normalized=True, nms_eta=1.0):
    """Greedy per-class suppression over score-sorted candidates.
    Returns (kept mask over the top_k sorted slots, their indices).
    ``nms_eta < 1`` decays the threshold after each kept box while it stays
    above 0.5 (reference: detection/nms_util.h NMSFast adaptive_threshold)."""
    k = min(top_k, scores.shape[0])
    top_scores, order = lax.top_k(scores, k)
    cand = boxes[order]
    iou = _pairwise_iou(cand, cand, normalized=normalized)
    valid = top_scores > score_threshold
    adaptive = nms_eta < 1.0

    def step(carry, i):
        kept, thr = carry
        # suppressed if any higher-scored kept candidate overlaps too much
        sup = jnp.any(kept & (iou[:, i] > thr) & (jnp.arange(k) < i))
        keep_i = valid[i] & ~sup
        if adaptive:
            thr = jnp.where(keep_i & (thr > 0.5), thr * nms_eta, thr)
        return (kept.at[i].set(keep_i), thr), keep_i

    kept0 = jnp.zeros(k, bool)
    thr0 = jnp.asarray(iou_threshold, jnp.float32)
    (kept, _), _ = lax.scan(step, (kept0, thr0), jnp.arange(k))
    return kept, order, top_scores


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None,
                   return_index=False):
    """reference: detection/multiclass_nms_op.cc (MultiClassNMS kernel).

    bboxes: [N, M, 4]; scores: [N, C, M].
    Fixed-size output: out [N, keep_top_k, 6] rows = (label, score,
    x1, y1, x2, y2), padded rows have label -1; counts [N] = valid rows
    (the reference's LoD offsets → explicit count vector).
    """
    def impl(bb, sc):
        n, c, m = sc.shape

        def per_image(boxes, cls_scores):
            labels_all, scores_all, boxes_all = [], [], []
            for cls in range(c):
                if cls == background_label:
                    continue
                kept, order, top_scores = _greedy_nms_mask(
                    boxes, cls_scores[cls], nms_threshold,
                    score_threshold, nms_top_k,
                    normalized=normalized, nms_eta=nms_eta)
                scores = jnp.where(kept, top_scores, -1.0)
                labels_all.append(jnp.full_like(scores, cls))
                scores_all.append(scores)
                boxes_all.append(boxes[order])
            all_scores = jnp.concatenate(scores_all)
            all_labels = jnp.concatenate(labels_all)
            all_boxes = jnp.concatenate(boxes_all, axis=0)
            kk = min(keep_top_k, all_scores.shape[0])
            best, idx = lax.top_k(all_scores, kk)
            valid = best >= 0
            out = jnp.concatenate([
                jnp.where(valid, all_labels[idx], -1.0)[:, None],
                jnp.where(valid, best, 0.0)[:, None],
                jnp.where(valid[:, None], all_boxes[idx], 0.0)], axis=1)
            if kk < keep_top_k:
                pad = jnp.zeros((keep_top_k - kk, 6), out.dtype)
                pad = pad.at[:, 0].set(-1.0)
                out = jnp.concatenate([out, pad], axis=0)
            return out, valid.sum()

        outs, counts = jax.vmap(per_image)(bb, sc)
        return outs, counts.astype(jnp.int32)
    return apply("multiclass_nms", impl, bboxes, scores)


# -- prior_box ----------------------------------------------------------------

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """reference: detection/prior_box_op.cc (SSD prior boxes)."""
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    min_sizes = [float(s) for s in np.atleast_1d(min_sizes)]
    max_sizes = [float(s) for s in np.atleast_1d(max_sizes)] if max_sizes \
        else []
    num_priors = len(ars) * len(min_sizes) + len(max_sizes)

    def impl(feat, img):
        fh, fw = feat.shape[2], feat.shape[3]
        ih, iw = img.shape[2], img.shape[3]
        step_h = steps[1] if steps[1] > 0 else ih / fh
        step_w = steps[0] if steps[0] > 0 else iw / fw
        cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
        cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
        cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")
        whs = []
        for ms in min_sizes:
            if min_max_aspect_ratios_order:
                whs.append((ms, ms))
                if max_sizes:
                    mx = max_sizes[min_sizes.index(ms)]
                    whs.append((float(np.sqrt(ms * mx)),) * 2)
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            else:
                for ar in ars:
                    whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
                if max_sizes:
                    mx = max_sizes[min_sizes.index(ms)]
                    whs.append((float(np.sqrt(ms * mx)),) * 2)
        wh = jnp.asarray(whs, jnp.float32)  # [P, 2]
        boxes = jnp.stack([
            (cxg[..., None] - wh[:, 0] / 2) / iw,
            (cyg[..., None] - wh[:, 1] / 2) / ih,
            (cxg[..., None] + wh[:, 0] / 2) / iw,
            (cyg[..., None] + wh[:, 1] / 2) / ih], axis=-1)  # [H, W, P, 4]
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               boxes.shape)
        return boxes, var
    return apply("prior_box", impl, input, image)


def box_coder(prior_box_t, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """reference: detection/box_coder_op.cc."""
    norm = 1.0 if box_normalized else 0.0

    def _cwh(b):
        w = b[..., 2] - b[..., 0] + (1.0 - norm)
        h = b[..., 3] - b[..., 1] + (1.0 - norm)
        cx = b[..., 0] + 0.5 * w
        cy = b[..., 1] + 0.5 * h
        return cx, cy, w, h

    if code_type == "encode_center_size":
        def impl(prior, pvar, target):
            pcx, pcy, pw, ph = _cwh(prior)           # [M,...]
            tcx, tcy, tw, th = _cwh(target[:, None, :] if target.ndim == 2
                                    else target)
            tx = (tcx - pcx) / pw
            ty = (tcy - pcy) / ph
            tw_ = jnp.log(jnp.abs(tw / pw))
            th_ = jnp.log(jnp.abs(th / ph))
            out = jnp.stack([tx, ty, tw_, th_], axis=-1)
            if pvar is not None:
                out = out / pvar
            return out
    else:  # decode_center_size
        def impl(prior, pvar, target):
            pcx, pcy, pw, ph = _cwh(prior)
            t = target
            if pvar is not None:
                t = t * pvar
            ocx = t[..., 0] * pw + pcx
            ocy = t[..., 1] * ph + pcy
            ow = jnp.exp(t[..., 2]) * pw
            oh = jnp.exp(t[..., 3]) * ph
            return jnp.stack([ocx - ow / 2, ocy - oh / 2,
                              ocx + ow / 2 - (1.0 - norm),
                              ocy + oh / 2 - (1.0 - norm)], axis=-1)
    return apply("box_coder", impl, prior_box_t, prior_box_var, target_box)


# -- yolov3_loss --------------------------------------------------------------

def _bce(pred_logit, target):
    p = _sigmoid(pred_logit)
    eps = 1e-7
    return -(target * jnp.log(p + eps) + (1 - target) * jnp.log(1 - p + eps))


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=False, name=None, scale_x_y=1.0):
    """reference: detection/yolov3_loss_op.cc.

    x: [N, A*(5+C), H, W] raw predictions for this scale;
    gt_box: [N, B, 4] (cx, cy, w, h normalized to [0,1]), zero-padded slots;
    gt_label: [N, B] int; anchors: full anchor list (pairs); anchor_mask:
    indices of this scale's anchors. Loss per the YOLOv3 paper: BCE on
    x/y/objectness/class, squared error on w/h, box-size weighting
    (2 - w*h), no-object loss ignored where best-gt IoU > ignore_thresh.
    """
    all_anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask = list(anchor_mask)
    A = len(mask)
    C = int(class_num)

    def impl(xr, gbox, glabel):
        n, _, h, w = xr.shape
        p = xr.reshape(n, A, 5 + C, h, w)
        input_h = float(h * downsample_ratio)
        input_w = float(w * downsample_ratio)
        masked = all_anchors[mask] / np.array([input_w, input_h], np.float32)
        an_w = jnp.asarray(masked[:, 0])      # [A] normalized
        an_h = jnp.asarray(masked[:, 1])

        valid = (gbox[..., 2] > 0) & (gbox[..., 3] > 0)      # [N, B]

        # -- best anchor per gt (shape-only IoU vs ALL anchors) ----------
        all_norm = jnp.asarray(
            all_anchors / np.array([input_w, input_h], np.float32))
        gw = gbox[..., 2][..., None]                          # [N,B,1]
        gh = gbox[..., 3][..., None]
        inter = jnp.minimum(gw, all_norm[:, 0]) * jnp.minimum(gh, all_norm[:, 1])
        union = gw * gh + all_norm[:, 0] * all_norm[:, 1] - inter
        shape_iou = inter / (union + 1e-9)                    # [N,B,Atot]
        best_anchor = jnp.argmax(shape_iou, axis=-1)          # [N,B]
        # position in this scale's mask (-1 if not ours)
        mask_arr = jnp.asarray(mask)
        in_mask = best_anchor[..., None] == mask_arr          # [N,B,A]
        local_a = jnp.argmax(in_mask, axis=-1)                # [N,B]
        responsible = valid & jnp.any(in_mask, axis=-1)

        gi = jnp.clip((gbox[..., 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gbox[..., 1] * h).astype(jnp.int32), 0, h - 1)

        # targets
        tx = gbox[..., 0] * w - gi
        ty = gbox[..., 1] * h - gj
        tw = jnp.log(gbox[..., 2] / (an_w[local_a] + 1e-9) + 1e-9)
        th = jnp.log(gbox[..., 3] / (an_h[local_a] + 1e-9) + 1e-9)
        box_w = 2.0 - gbox[..., 2] * gbox[..., 3]             # size weight

        # gather predictions at assigned cells: [N, B, ...]
        bidx = jnp.arange(n)[:, None]
        px = p[bidx, local_a, 0, gj, gi]
        py = p[bidx, local_a, 1, gj, gi]
        pw = p[bidx, local_a, 2, gj, gi]
        ph = p[bidx, local_a, 3, gj, gi]
        pcls = jnp.moveaxis(p[:, :, 5:], 2, -1)[bidx, local_a, gj, gi]

        rmask = responsible.astype(xr.dtype)
        loss_xy = (_bce(px, tx) + _bce(py, ty)) * box_w * rmask
        loss_wh = ((pw - tw) ** 2 + (ph - th) ** 2) * 0.5 * box_w * rmask
        smooth = 1.0 / max(C, 1) if use_label_smooth else 0.0
        onehot = jax.nn.one_hot(glabel, C) * (1 - 2 * smooth) + smooth
        loss_cls = jnp.sum(_bce(pcls, onehot), axis=-1) * rmask

        # objectness: target 1 at responsible cells; 0 elsewhere unless the
        # predicted box overlaps some gt above ignore_thresh
        obj_logit = p[:, :, 4]                                # [N,A,H,W]
        tobj = jnp.zeros((n, A, h, w), xr.dtype)
        tobj = tobj.at[bidx, local_a, gj, gi].max(rmask)

        # predicted boxes for ignore mask (no grad needed; detached values)
        grid_x = jnp.arange(w, dtype=xr.dtype).reshape(1, 1, 1, w)
        grid_y = jnp.arange(h, dtype=xr.dtype).reshape(1, 1, h, 1)
        bx = (_sigmoid(p[:, :, 0]) + grid_x) / w
        by = (_sigmoid(p[:, :, 1]) + grid_y) / h
        bw = jnp.exp(jnp.clip(p[:, :, 2], -10, 10)) * an_w.reshape(1, A, 1, 1)
        bh = jnp.exp(jnp.clip(p[:, :, 3], -10, 10)) * an_h.reshape(1, A, 1, 1)
        pred_xyxy = jnp.stack([bx - bw / 2, by - bh / 2,
                               bx + bw / 2, by + bh / 2], -1)  # [N,A,H,W,4]
        g_xyxy = jnp.stack([gbox[..., 0] - gbox[..., 2] / 2,
                            gbox[..., 1] - gbox[..., 3] / 2,
                            gbox[..., 0] + gbox[..., 2] / 2,
                            gbox[..., 1] + gbox[..., 3] / 2], -1)  # [N,B,4]

        def img_iou(pb, gb, v):
            i = _pairwise_iou(pb.reshape(-1, 4), gb)          # [AHW, B]
            i = jnp.where(v[None, :], i, 0.0)
            return i.max(axis=-1).reshape(A, h, w)
        best_iou = jax.vmap(img_iou)(lax.stop_gradient(pred_xyxy),
                                     g_xyxy, valid)
        noobj_mask = ((best_iou < ignore_thresh) & (tobj < 0.5)
                      ).astype(xr.dtype)
        loss_obj = (_bce(obj_logit, jnp.ones_like(tobj)) * tobj
                    + _bce(obj_logit, jnp.zeros_like(tobj)) * noobj_mask)

        per_img = (loss_xy.sum(axis=1) + loss_wh.sum(axis=1)
                   + loss_cls.sum(axis=1)
                   + loss_obj.sum(axis=(1, 2, 3)))
        return per_img
    if gt_score is not None:
        return apply("yolov3_loss", lambda a, b, c, s: impl(a, b, c),
                     x, gt_box, gt_label, gt_score)
    return apply("yolov3_loss", impl, x, gt_box, gt_label)
