"""Beam-search decode ops (reference: paddle/fluid/operators/
beam_search_op.cc, beam_search_decode_op.cc, gather_tree_op.cc,
ctc_align_op.cc, edit_distance_op.cc).

The reference's beam_search mutates LoD to track per-beam lineage; here
lineage is an explicit static [T, B, W] parents tensor and the final
backtrace is one gather_tree scan — the TPU form used by dynamic_decode.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .dispatch import apply
from ..core.tensor import Tensor


def gather_tree(ids, parents):
    """reference: gather_tree_op.cc — backtrace beam lineage.
    ids/parents: [T, B, W] (step-major). Returns full sequences [T, B, W]
    where column w holds the tokens along the ancestry of final beam w."""
    def impl(idt, par):
        T = idt.shape[0]

        def step(beam, t):
            # beam: [B, W] current beam slot per final column
            tok = jnp.take_along_axis(idt[t], beam, axis=1)
            nxt = jnp.take_along_axis(par[t], beam, axis=1)
            return nxt.astype(beam.dtype), tok

        init = jnp.broadcast_to(
            jnp.arange(idt.shape[2], dtype=idt.dtype)[None, :],
            idt.shape[1:])
        _, toks = lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]
    return apply("gather_tree", impl, ids, parents)


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, return_parent_idx=True):
    """reference: beam_search_op.cc — ONE expansion step.

    pre_ids [B, W], pre_scores [B, W], scores [B, W, V] (log-probs of the
    next token per live beam; already accumulated when is_accumulated).
    Selects the top ``beam_size`` of the W*V candidates per batch row.
    Finished beams (pre_id == end_id) only propagate themselves.
    Returns (selected_ids [B, W], selected_scores [B, W],
    parent_idx [B, W]).
    """
    W = int(beam_size)

    def impl(p_ids, p_sc, sc):
        B, Wv, V = sc.shape
        total = sc if is_accumulated else p_sc[..., None] + sc
        finished = p_ids == end_id
        # a finished beam contributes exactly one candidate: itself
        only_end = jnp.full((B, Wv, V), -jnp.inf, total.dtype)
        only_end = only_end.at[:, :, end_id].set(p_sc)
        cand = jnp.where(finished[..., None], only_end, total)
        flat = cand.reshape(B, Wv * V)
        top_sc, top_ix = lax.top_k(flat, W)
        parent = (top_ix // V).astype(jnp.int64)
        token = (top_ix % V).astype(p_ids.dtype)
        return token, top_sc, parent
    return apply("beam_search", impl, pre_ids, pre_scores, scores)


def beam_search_decode(ids, parents, scores, beam_size=None, end_id=0):
    """reference: beam_search_decode_op.cc — backtrace all steps into final
    sequences + their scores. ids/parents [T, B, W] (from beam_search
    steps), scores [B, W] final accumulated scores. Returns
    (sequences [T, B, W], scores [B, W])."""
    seqs = gather_tree(ids, parents)
    return seqs, scores


def ctc_align(input, blank=0, merge_repeated=True, padding_value=0,
              lengths=None, name=None):
    """reference: ctc_align_op.cc — collapse repeats then drop blanks,
    left-packing survivors ([B, T] + lengths convention). Returns
    (aligned [B, T], new_lengths [B])."""
    def impl(ids, *rest):
        lens = rest[0] if rest else None
        B, T = ids.shape
        t = jnp.arange(T)[None, :]
        valid = t < lens[:, None] if lens is not None else jnp.ones(
            (B, T), bool)
        prev = jnp.concatenate(
            [jnp.full((B, 1), -1, ids.dtype), ids[:, :-1]], axis=1)
        keep = valid & (ids != blank)
        if merge_repeated:
            keep = keep & (ids != prev)
        new_len = keep.sum(axis=1)
        pos = jnp.cumsum(keep, axis=1) - 1
        dest = jnp.where(keep, pos, T - 1)
        out = jnp.full_like(ids, padding_value)
        b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], ids.shape)
        # left-pack: every kept token writes its unique slot; the T-1 junk
        # slot is overwritten last by a real token only if it owns it
        out = out.at[b_idx, dest].set(
            jnp.where(keep, ids, padding_value))
        fixl = (new_len == T)
        return out, new_len.astype(jnp.int64) + 0 * fixl
    args = (input,) + ((lengths,) if lengths is not None else ())
    out, nl = apply("ctc_align", impl, *args)
    return out, nl


def edit_distance(input, label, normalized=True, input_length=None,
                  label_length=None, name=None):
    """reference: edit_distance_op.cc — Levenshtein distance per batch row
    over the padded+lengths convention. Returns (dist [B, 1],
    seq_num [1])."""
    def impl(hyp, ref, *rest):
        it = iter(rest)
        hlen = next(it) if input_length is not None else None
        rlen = next(it) if label_length is not None else None
        B, Th = hyp.shape
        Tr = ref.shape[1]
        if hlen is None:
            hlen = jnp.full((B,), Th, jnp.int32)
        if rlen is None:
            rlen = jnp.full((B,), Tr, jnp.int32)
        hlen = hlen.astype(jnp.int32)
        rlen = rlen.astype(jnp.int32)

        # DP over ref positions; row carries distances for hyp prefix
        def row_step(carry, j):
            drow = carry                     # [B, Th+1] distances for ref[:j]
            jj = j + 1

            def col_step(dprev, i):
                # dprev: [B] = D[j+1][i]; returns D[j+1][i+1]
                sub = drow[:, i] + (hyp[:, i] != ref[:, j])
                ins = dprev + 1
                dele = drow[:, i + 1] + 1
                out = jnp.minimum(jnp.minimum(sub, ins), dele)
                # clamp: beyond valid ref length the row is just copied
                out = jnp.where(j < rlen, out, drow[:, i + 1])
                return out, out

            d0 = jnp.where(j < rlen, jnp.full((B,), jj, jnp.int32),
                           drow[:, 0])
            _, cols = lax.scan(col_step, d0, jnp.arange(Th))
            new_row = jnp.concatenate([d0[:, None], cols.T], axis=1)
            return new_row.astype(jnp.int32), None

        row0 = jnp.broadcast_to(jnp.arange(Th + 1, dtype=jnp.int32)[None, :],
                                (B, Th + 1))
        # positions past the hyp length must not contribute: we take the
        # entry at index hlen at the end, so padding columns are ignored
        final, _ = lax.scan(row_step, row0, jnp.arange(Tr))
        d = jnp.take_along_axis(final, hlen[:, None], axis=1)[:, 0]
        d = d.astype(jnp.float32)
        if normalized:
            d = d / jnp.maximum(rlen.astype(jnp.float32), 1.0)
        return d[:, None], jnp.asarray([B], jnp.int64)
    args = [input, label]
    if input_length is not None:
        args.append(input_length)
    if label_length is not None:
        args.append(label_length)
    return apply("edit_distance", impl, *args)
