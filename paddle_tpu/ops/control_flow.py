"""Control flow ops: cond / while_loop / case / switch_case.

TPU-native equivalent of the reference's sub-block control flow
(reference: paddle/fluid/operators/controlflow/conditional_block_op.cc,
while_op.cc — ops that re-entrantly run sub-Blocks through the Executor;
Python surface python/paddle/fluid/layers/control_flow.py cond :2334,
while_loop :1076, case :2788, switch_case :3099).

Three execution contexts:
1. **Eager with concrete predicate**: plain Python dispatch — the branch taken
   is tape-recorded, so autograd works exactly like any eager code.
2. **Traced (to_static / inside jit)**: predicates are tracers; lowers to
   ``lax.cond`` / ``lax.while_loop`` over the flattened raw leaves. cond is
   reverse-differentiable; while_loop is forward-only under reverse-mode AD
   (XLA's model) — loops that need training gradients should be expressed
   with lax.scan-style RNN layers or run in eager mode.
3. **Static Program**: the branch builders are traced into sub-Programs
   (the analog of the reference's sub-Blocks) and recorded as ONE composite
   op whose implementation replays the sub-Programs under lax.cond /
   lax.while_loop; external variables/parameters become the op's inputs.
"""
from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.tree_util import tree_flatten, tree_unflatten

from ..core.tensor import Tensor
from .dispatch import apply, in_dygraph_mode

__all__ = ["cond", "while_loop", "case", "switch_case", "increment",
           "array_write", "array_read", "array_length", "create_array"]


def _is_leaf(x):
    return isinstance(x, Tensor)


def _flatten_out(out):
    leaves, td = tree_flatten(out, is_leaf=_is_leaf)
    raws = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
            for l in leaves]
    return raws, td


def _is_tracer(x):
    if isinstance(x, Tensor):
        x = x._data
    return isinstance(x, jax.core.Tracer)


def _static_var(x):
    from ..static.graph import Variable
    return isinstance(x, Variable)


# -- cond ---------------------------------------------------------------------

def cond(pred, true_fn=None, false_fn=None, name=None):
    """reference: fluid/layers/control_flow.py:2334 cond."""
    if _static_var(pred):
        return _static_cond(pred, true_fn, false_fn)
    raw = pred._data if isinstance(pred, Tensor) else pred
    if not isinstance(raw, jax.core.Tracer):
        take_true = bool(np.asarray(raw))
        if take_true:
            return true_fn() if true_fn is not None else None
        return false_fn() if false_fn is not None else None
    # traced: run both branches under lax.cond
    meta = {}

    def t_branch(_):
        raws, td = _flatten_out(true_fn())
        meta["td"] = td
        return tuple(raws)

    def f_branch(_):
        raws, td = _flatten_out(false_fn())
        meta.setdefault("td", td)
        return tuple(raws)

    out_raws = lax.cond(raw.astype(bool).reshape(()), t_branch, f_branch, 0)
    outs = [Tensor(r) for r in out_raws]
    return tree_unflatten(meta["td"], outs)


def _sub_capture(fn, args=()):
    """Trace a branch builder into a fresh sub-Program (the reference's
    sub-Block: conditional_block_op.cc)."""
    from ..static.graph import Program, program_guard, Variable
    sub = Program()
    with program_guard(sub):
        out = fn(*args)
    leaves, td = tree_flatten(out, is_leaf=lambda x: isinstance(x, (Tensor,)))
    return sub, leaves, td


def _external_leaves(sub) -> List[Any]:
    """Variables from the outer program + parameter Tensors used by sub."""
    from ..static.graph import Variable
    seen, ext = set(), []
    for op in sub.ops:
        for leaf in op.arg_leaves:
            if isinstance(leaf, Variable) and leaf._program is not sub:
                if id(leaf) not in seen:
                    seen.add(id(leaf))
                    ext.append(leaf)
            elif isinstance(leaf, Tensor):
                if id(leaf) not in seen:
                    seen.add(id(leaf))
                    ext.append(leaf)
    return ext


def _replay_sub(sub, ext, ext_raws, extra_env=None, extra_penv=None):
    from ..static.graph import Variable
    from ..static.executor import _replay
    env, penv = dict(extra_env or {}), dict(extra_penv or {})
    for leaf, rawv in zip(ext, ext_raws):
        if isinstance(leaf, Variable):
            env[id(leaf)] = rawv
        else:
            penv[id(leaf)] = rawv
    _replay(sub, env, penv)
    return env, penv


def _out_raws(env, penv, leaves):
    from ..static.graph import Variable
    out = []
    for l in leaves:
        if isinstance(l, Variable):
            out.append(env[id(l)])
        elif isinstance(l, Tensor):
            out.append(penv.get(id(l), l._data))
        else:
            out.append(l)
    return out


def _outer_out_leaves(sub, leaves):
    """Output leaves that are passthrough captures (outer Variables / param
    Tensors returned unchanged) — they must be bound as inputs too."""
    from ..static.graph import Variable
    outer = []
    for l in leaves:
        if isinstance(l, Variable) and l._program is not sub:
            outer.append(l)
        elif isinstance(l, Tensor):
            outer.append(l)
    return outer


def _static_cond(pred, true_fn, false_fn):
    sub_t, out_t, td_t = _sub_capture(true_fn)
    sub_f, out_f, td_f = _sub_capture(false_fn)
    ext = []
    seen = set()
    for e in (_external_leaves(sub_t) + _external_leaves(sub_f)
              + _outer_out_leaves(sub_t, out_t)
              + _outer_out_leaves(sub_f, out_f)):
        if id(e) not in seen:
            seen.add(id(e))
            ext.append(e)

    def composite(pred_raw, *ext_raws):
        def tb(_):
            env, penv = _replay_sub(sub_t, ext, ext_raws)
            return tuple(_out_raws(env, penv, out_t))

        def fb(_):
            env, penv = _replay_sub(sub_f, ext, ext_raws)
            return tuple(_out_raws(env, penv, out_f))
        return lax.cond(pred_raw.astype(bool).reshape(()), tb, fb, 0)

    res = apply("cond", composite, pred, *ext)
    leaves = list(res) if isinstance(res, (list, tuple)) else [res]
    return tree_unflatten(td_t, leaves)


# -- while_loop ---------------------------------------------------------------

def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence,
               is_test=False, name=None):
    """reference: fluid/layers/control_flow.py:1076 while_loop."""
    loop_vars = list(loop_vars)
    if any(_static_var(v) for v in tree_flatten(
            loop_vars, is_leaf=_is_leaf)[0]):
        return _static_while(cond_fn, body_fn, loop_vars)

    leaves, td = tree_flatten(loop_vars, is_leaf=_is_leaf)
    if not any(_is_tracer(l) for l in leaves):
        # eager: a real Python loop, fully tape-recorded
        state = loop_vars
        while bool(np.asarray(_as_scalar(cond_fn(*state)))):
            out = body_fn(*state)
            state = list(out) if isinstance(out, (list, tuple)) else [out]
        return state
    # traced: lax.while_loop over raw leaves
    raws = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
            for l in leaves]

    def wrap(raw_state):
        ts = [Tensor(r) for r in raw_state]
        return tree_unflatten(td, ts)

    def c(raw_state):
        out = cond_fn(*wrap(raw_state))
        return _as_raw_scalar(out)

    def b(raw_state):
        out = body_fn(*wrap(raw_state))
        out_list = list(out) if isinstance(out, (list, tuple)) else [out]
        new_leaves, _ = tree_flatten(out_list, is_leaf=_is_leaf)
        return tuple(l._data if isinstance(l, Tensor) else jnp.asarray(l)
                     for l in new_leaves)

    final = lax.while_loop(c, b, tuple(raws))
    return tree_unflatten(td, [Tensor(r) for r in final])


def _as_scalar(x):
    return x._data if isinstance(x, Tensor) else x


def _as_raw_scalar(x):
    r = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return r.astype(bool).reshape(())


def _static_while(cond_fn, body_fn, loop_vars):
    from ..static.graph import Variable
    lv_leaves, td = tree_flatten(loop_vars, is_leaf=_is_leaf)
    sub_c, out_c, _ = _sub_capture(cond_fn, loop_vars)
    sub_b, out_b, td_b = _sub_capture(body_fn, loop_vars)
    lv_ids = {id(l) for l in lv_leaves}
    ext = []
    seen = set()
    for e in (_external_leaves(sub_c) + _external_leaves(sub_b)
              + _outer_out_leaves(sub_c, out_c)
              + _outer_out_leaves(sub_b, out_b)):
        if id(e) not in seen and id(e) not in lv_ids:
            seen.add(id(e))
            ext.append(e)

    def composite(*all_raws):
        n = len(lv_leaves)
        lv_raws, ext_raws = all_raws[:n], all_raws[n:]

        def lv_envs(state):
            # loop vars may be graph Variables or concrete Tensors (a
            # counter mixed with an eager accumulator) — bind each in the
            # environment _replay resolves it from
            env = {id(v): r for v, r in zip(lv_leaves, state)
                   if isinstance(v, Variable)}
            penv = {id(v): r for v, r in zip(lv_leaves, state)
                    if isinstance(v, Tensor)}
            return env, penv

        def c(state):
            e0, p0 = lv_envs(state)
            env, penv = _replay_sub(sub_c, ext, ext_raws, e0, p0)
            return _out_raws(env, penv, out_c)[0].astype(bool).reshape(())

        def b(state):
            e0, p0 = lv_envs(state)
            env, penv = _replay_sub(sub_b, ext, ext_raws, e0, p0)
            outs = _out_raws(env, penv, out_b)
            return tuple(o.astype(s.dtype) if hasattr(o, "astype") else o
                         for o, s in zip(outs, state))

        return lax.while_loop(c, b, tuple(lv_raws))

    res = apply("while_loop", composite, *(lv_leaves + ext))
    leaves = list(res) if isinstance(res, (list, tuple)) else [res]
    return tree_unflatten(td_b, leaves)


# -- case / switch_case -------------------------------------------------------

def case(pred_fn_pairs, default=None, name=None):
    """reference: control_flow.py:2788 — first true predicate wins."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if not rest:
        if default is None:
            return cond(pred, fn, fn)
        return cond(pred, fn, default)
    return cond(pred, fn, lambda: case(rest, default))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference: control_flow.py:3099."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        pairs = list(enumerate(branch_fns)) if not isinstance(
            branch_fns[0], (tuple, list)) else [tuple(p) for p in branch_fns]
    idx_raw = branch_index._data if isinstance(branch_index, Tensor) \
        else branch_index
    if not isinstance(idx_raw, jax.core.Tracer) and not _static_var(
            branch_index):
        i = int(np.asarray(idx_raw))
        for k, fn in pairs:
            if k == i:
                return fn()
        if default is not None:
            return default()
        return pairs[-1][1]()  # reference: last branch is the fallback
    # traced: nest conds
    def build(remaining):
        (k, fn) = remaining[0]
        if len(remaining) == 1:
            if default is not None:
                return cond(branch_index == k, fn, default)
            return fn()
        return cond(branch_index == k, fn, lambda: build(remaining[1:]))
    return build(pairs)


# -- tensor-array helpers (reference: controlflow/write_to_array etc.) -------

def create_array(dtype="float32", initialized_list=None):
    """reference: fluid/layers/control_flow.py create_array — a Python list
    plays the LoDTensorArray role (static shapes make a real tensor-array op
    unnecessary on XLA; loops that build arrays should use lax.scan RNNs)."""
    return list(initialized_list or [])


def array_write(x, i, array=None):
    if array is None:
        array = []
    i = int(np.asarray(_as_scalar(i)))
    while len(array) <= i:
        array.append(None)
    array[i] = x
    return array


def array_read(array, i):
    return array[int(np.asarray(_as_scalar(i)))]


def array_length(array):
    from . import creation
    return creation.to_tensor(np.int64(len(array)))


def increment(x, value=1.0):
    """reference: operators/increment_op — in-place add on a 1-element
    tensor."""
    from .dispatch import apply as _apply
    out = _apply("increment", lambda a: a + np.asarray(value, a.dtype), x)
    if isinstance(x, Tensor):
        x._swap_payload(out)
        return x
    return out
