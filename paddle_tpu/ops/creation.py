"""Tensor creation ops.

Parity targets (reference op registrations, SURVEY Appendix A math/creation
group): fill_constant, uniform_random, gaussian_random, randint, randperm,
linspace, eye, tril_triu, assign, one_hot_v2, arange/range, bernoulli,
multinomial, truncated_gaussian_random (paddle/fluid/operators/*).
Random ops draw keys from the global Generator (core/generator.py) so
``paddle.seed`` controls them, like the reference's seeded Generator
(framework/generator.cc).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtypes as _dt
from ..core import generator as _gen
from .dispatch import apply, apply_raw


def _shape_list(shape):
    if isinstance(shape, Tensor):
        shape = shape.numpy().tolist()  # noqa: PTA002 -- shapes must be concrete host values
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._data if isinstance(s, Tensor) else s) for s in shape]


def _dtype_or_default(dtype):
    d = _dt.convert_dtype(dtype)
    return d if d is not None else _dt.get_default_dtype()


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity (reference: python/paddle/tensor/creation.py)."""
    if isinstance(data, Tensor):
        t = Tensor(data._data, dtype=dtype, stop_gradient=stop_gradient)
        return t
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def zeros(shape, dtype=None, name=None):
    d = _dtype_or_default(dtype)
    return apply("fill_constant", lambda: jnp.zeros(_shape_list(shape), d))


def ones(shape, dtype=None, name=None):
    d = _dtype_or_default(dtype)
    return apply("fill_constant", lambda: jnp.ones(_shape_list(shape), d))


def full(shape, fill_value, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)
    if isinstance(fill_value, Tensor):
        if d is None:
            fd = fill_value._data.dtype
            d = (np.dtype("bool") if fd == np.bool_
                 else np.dtype("int64") if jnp.issubdtype(fd, jnp.integer)
                 else _dt.get_default_dtype())
        fill_value = fill_value._data  # stays on device; jnp.full broadcasts
    if d is None:
        d = (np.dtype("bool") if isinstance(fill_value, bool)
             else np.dtype("int64") if isinstance(fill_value, int)
             else _dt.get_default_dtype())
    return apply("fill_constant", lambda: jnp.full(_shape_list(shape), fill_value, d))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype, name)


def zeros_like(x, dtype=None, name=None):
    return apply("fill_zeros_like", lambda a: jnp.zeros_like(a, dtype=_dt.convert_dtype(dtype)), x)


def ones_like(x, dtype=None, name=None):
    return apply("fill_ones_like", lambda a: jnp.ones_like(a, dtype=_dt.convert_dtype(dtype)), x)


def full_like(x, fill_value, dtype=None, name=None):
    return apply("fill_any_like",
                 lambda a: jnp.full_like(a, fill_value, dtype=_dt.convert_dtype(dtype)), x)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype, name)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    start = start.item() if isinstance(start, Tensor) else start  # noqa: PTA002 -- arange output shape depends on the values
    end = end.item() if isinstance(end, Tensor) else end  # noqa: PTA002 -- arange output shape depends on the values
    step = step.item() if isinstance(step, Tensor) else step  # noqa: PTA002 -- arange output shape depends on the values
    d = _dt.convert_dtype(dtype)
    if d is None:
        d = (np.dtype("int64") if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
             else _dt.get_default_dtype())
    return apply("range", lambda: jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None):
    start = start._data if isinstance(start, Tensor) else start
    stop = stop._data if isinstance(stop, Tensor) else stop
    num = int(num.item() if isinstance(num, Tensor) else num)  # noqa: PTA002 -- num is the output length (a shape) and must be concrete
    d = _dtype_or_default(dtype)
    return apply("linspace", lambda: jnp.linspace(start, stop, num, dtype=d))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    d = _dtype_or_default(dtype)
    return apply("eye", lambda: jnp.eye(num_rows, num_columns, dtype=d))


def diag(x, offset=0, padding_value=0, name=None):
    def impl(a):
        if a.ndim == 1 and padding_value != 0:
            n = a.shape[0] + abs(offset)
            base = jnp.full((n, n), padding_value, a.dtype)
            return base + jnp.diag(a, offset) - jnp.diag(jnp.full((a.shape[0],), padding_value, a.dtype), offset)
        return jnp.diag(a, offset)
    return apply("diag_v2", impl, x)


def diagflat(x, offset=0, name=None):
    return apply("diagflat", lambda a: jnp.diagflat(a, offset), x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    def impl(a):
        flat = a.reshape(-1, a.shape[-1])
        mats = jax.vmap(lambda v: jnp.diag(v, offset))(flat)
        mats = mats.reshape(a.shape[:-1] + mats.shape[-2:])
        if (dim1, dim2) != (-2, -1):
            mats = jnp.moveaxis(mats, (-2, -1), (dim1, dim2))
        return mats
    return apply("diag_embed", impl, x)


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    return apply("meshgrid", lambda *xs: list(jnp.meshgrid(*xs, indexing="ij")), *tensors)


def tril(x, diagonal=0, name=None):
    return apply("tril_triu", lambda a: jnp.tril(a, diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply("tril_triu", lambda a: jnp.triu(a, diagonal), x)


def assign(x, output=None):
    """reference: operators/assign_op.cc; copies input."""
    src = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
    out = apply("assign", lambda a: a + 0 if _dt.is_floating(a.dtype) else jnp.array(a), src)
    if output is not None:
        output._swap_payload(out)
        return output
    return out


def clone(x, name=None):
    return x.clone()


def numel(x, name=None):
    return apply("size", lambda a: jnp.asarray(a.size, jnp.int64), x)


def one_hot(x, num_classes, name=None):
    return apply("one_hot_v2",
                 lambda a: jax.nn.one_hot(a, num_classes, dtype=_dt.get_default_dtype()), x)


# -- random ------------------------------------------------------------------

def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    d = _dtype_or_default(dtype)
    key = _gen.next_key() if seed == 0 else jax.random.PRNGKey(seed)
    return apply_raw("uniform_random",
                     lambda: jax.random.uniform(key, _shape_list(shape), d, min, max))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        shape = (mean.shape if isinstance(mean, Tensor) else std.shape)
        key = _gen.next_key()
        return apply_raw("gaussian_random",
                         lambda m, s: jax.random.normal(key, _shape_list(shape),
                                                        _dt.get_default_dtype()) * s + m,
                         mean, std)
    d = _dt.get_default_dtype()
    key = _gen.next_key()
    return apply_raw("gaussian_random",
                     lambda: jax.random.normal(key, _shape_list(shape), d) * std + mean)


def randn(shape, dtype=None, name=None):
    d = _dtype_or_default(dtype)
    key = _gen.next_key()
    return apply_raw("gaussian_random", lambda: jax.random.normal(key, _shape_list(shape), d))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype, name)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = _dt.convert_dtype(dtype) or np.dtype("int64")
    key = _gen.next_key()
    return apply_raw("randint",
                     lambda: jax.random.randint(key, _shape_list(shape), low, high, d))


def randperm(n, dtype="int64", name=None):
    key = _gen.next_key()
    return apply_raw("randperm",
                     lambda: jax.random.permutation(key, n).astype(_dt.convert_dtype(dtype)))


def bernoulli(x, name=None):
    key = _gen.next_key()
    return apply_raw("bernoulli",
                     lambda p: jax.random.bernoulli(key, p).astype(p.dtype), x)


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = _gen.next_key()

    def impl(probs):
        logits = jnp.log(jnp.maximum(probs, 1e-30))
        if replacement:
            return jax.random.categorical(key, logits, axis=-1,
                                          shape=(num_samples,) + probs.shape[:-1]).T \
                if probs.ndim > 1 else jax.random.categorical(
                    key, logits, shape=(num_samples,))
        # without replacement: gumbel top-k trick
        g = jax.random.gumbel(key, probs.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx
    out = apply_raw("multinomial", impl, x)
    return out.astype("int64")


def truncated_normal(shape, mean=0.0, std=1.0, dtype=None, name=None):
    d = _dtype_or_default(dtype)
    key = _gen.next_key()
    return apply_raw(
        "truncated_gaussian_random",
        lambda: jax.random.truncated_normal(key, -2.0, 2.0, _shape_list(shape), d) * std + mean)


def uniform_(x, min=-1.0, max=1.0, seed=0):
    x.set_value(uniform(x.shape, x.dtype, min, max, seed))
    return x


def normal_(x, mean=0.0, std=1.0):
    x.set_value(normal(mean, std, x.shape))
    return x


def zero_(x):
    x.set_value(zeros(x.shape, x.dtype))
    return x


def fill_(x, value):
    x.set_value(full(x.shape, value, x.dtype))
    return x
