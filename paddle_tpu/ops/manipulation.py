"""Shape / layout manipulation ops.

Parity targets: reshape2, transpose2, concat, split, stack, unstack, unbind,
squeeze2, unsqueeze2, flatten_contiguous_range, tile, expand_v2, flip, roll,
slice, strided_slice, pad/pad3d, pixel_shuffle, shuffle_channel, unfold,
space_to_depth, shard_index (reference: paddle/fluid/operators/*.cc per name).
"""
from __future__ import annotations

import builtins

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .dispatch import apply


slice_builtin = builtins.slice


def _int(v):
    return int(v.item() if isinstance(v, Tensor) else v)  # noqa: PTA002 -- shape/axis arguments must be concrete host values


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]  # noqa: PTA001,PTA002 -- shapes must be concrete host values
    return [_int(s) for s in shape]


def reshape(x, shape, name=None):
    s = _shape_list(shape)
    return apply("reshape2", lambda a: jnp.reshape(a, s), x)


def reshape_(x, shape, name=None):
    x._swap_payload(reshape(x, shape))
    return x


def transpose(x, perm, name=None):
    p = [_int(i) for i in perm]
    return apply("transpose2", lambda a: jnp.transpose(a, p), x)


def t(x, name=None):
    """reference: Tensor.t contract — 0/1-D pass through, 2-D transpose,
    higher ranks raise (use transpose)."""
    if len(x.shape) > 2:
        raise ValueError(
            f"t() expects a tensor with <= 2 dims, got {len(x.shape)} "
            f"(reference Tensor.t contract); use transpose")

    def impl(a):
        if a.ndim < 2:
            return a
        return a.T
    return apply("t", impl, x)


def moveaxis(x, source, destination, name=None):
    return apply("moveaxis", lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply("swapaxes", lambda a: jnp.swapaxes(a, axis0, axis1), x)


def concat(x, axis=0, name=None):
    ax = _int(axis)
    return apply("concat", lambda xs: jnp.concatenate(xs, axis=ax), list(x))


def stack(x, axis=0, name=None):
    return apply("stack", lambda xs: jnp.stack(xs, axis=axis), list(x))


def hstack(x, name=None):
    return apply("hstack", lambda xs: jnp.hstack(xs), list(x))


def vstack(x, name=None):
    return apply("vstack", lambda xs: jnp.vstack(xs), list(x))


def split(x, num_or_sections, axis=0, name=None):
    ax = _int(axis)

    def impl(a):
        if isinstance(num_or_sections, int):
            return list(jnp.split(a, num_or_sections, axis=ax))
        secs = [_int(s) if not isinstance(s, Tensor) else int(s.item())  # noqa: PTA002 -- split points are shapes; must be concrete
                for s in num_or_sections]
        total = a.shape[ax]
        if -1 in secs:
            known = np.sum([s for s in secs if s != -1])
            secs = [s if s != -1 else total - known for s in secs]
        points = np.cumsum(secs)[:-1].tolist()
        return list(jnp.split(a, points, axis=ax))
    return apply("split", impl, x)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis, name)


def unstack(x, axis=0, num=None, name=None):
    def impl(a):
        n = a.shape[axis]
        return [jnp.squeeze(s, axis) for s in jnp.split(a, n, axis=axis)]
    return apply("unstack", impl, x)


def unbind(input, axis=0):
    return unstack(input, axis)


def squeeze(x, axis=None, name=None):
    def impl(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(ax % a.ndim for ax in (_int(v) for v in axes) if a.shape[ax % a.ndim] == 1)
        return jnp.squeeze(a, axes) if axes else a
    return apply("squeeze2", impl, x)


def squeeze_(x, axis=None, name=None):
    x._swap_payload(squeeze(x, axis))
    return x


def unsqueeze(x, axis, name=None):
    def impl(a):
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        out = a
        for ax in sorted(_int(v) for v in axes):
            out = jnp.expand_dims(out, ax)
        return out
    return apply("unsqueeze2", impl, x)


def unsqueeze_(x, axis, name=None):
    x._swap_payload(unsqueeze(x, axis))
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def impl(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return apply("flatten_contiguous_range", impl, x)


def tile(x, repeat_times, name=None):
    reps = _shape_list(repeat_times)
    return apply("tile", lambda a: jnp.tile(a, reps), x)


def expand(x, shape, name=None):
    s = _shape_list(shape)

    def impl(a):
        tgt = list(s)
        # -1 means keep original dim (paddle semantics)
        offset = len(tgt) - a.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = a.shape[i - offset]
        return jnp.broadcast_to(a, tgt)
    return apply("expand_v2", impl, x)


def expand_as(x, y, name=None):
    return apply("expand_as_v2", lambda a, b: jnp.broadcast_to(a, b.shape), x, y)


def broadcast_to(x, shape, name=None):
    return expand(x, shape, name)


def broadcast_tensors(input, name=None):
    return apply("broadcast_tensors", lambda xs: list(jnp.broadcast_arrays(*xs)), list(input))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply("flip", lambda a: jnp.flip(a, tuple(_int(v) for v in axes)), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90", lambda a: jnp.rot90(a, k, axes), x)


def roll(x, shifts, axis=None, name=None):
    return apply("roll", lambda a: jnp.roll(a, shifts, axis), x)


def slice(input, axes, starts, ends):
    """reference: operators/slice_op.cc."""
    axes = [_int(a) for a in axes]
    starts = [_int(s) for s in starts]
    ends = [_int(e) for e in ends]

    def impl(a):
        idx = [slice_builtin(None)] * a.ndim
        for ax, st, en in zip(axes, starts, ends):
            idx[ax] = slice_builtin(st, en)
        return a[tuple(idx)]
    return apply("slice", impl, input)


def strided_slice(x, axes, starts, ends, strides, name=None):
    axes = [_int(a) for a in axes]
    starts = [_int(s) for s in starts]
    ends = [_int(e) for e in ends]
    strides = [_int(s) for s in strides]

    def impl(a):
        idx = [slice_builtin(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = slice_builtin(st, en, sd)
        return a[tuple(idx)]
    return apply("strided_slice", impl, x)


def crop(x, shape=None, offsets=None, name=None):
    s = _shape_list(shape)
    offs = [0] * len(s) if offsets is None else [_int(o) for o in offsets]

    def impl(a):
        idx = tuple(slice_builtin(o, o + (d if d != -1 else a.shape[i] - o))
                    for i, (o, d) in enumerate(zip(offs, s)))
        return a[idx]
    return apply("crop_tensor", impl, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """paddle.nn.functional.pad semantics (reference: operators/pad3d_op.cc):
    `pad` is [left, right, top, bottom, ...] over trailing spatial dims when
    len(pad) < 2*ndim, else per-dim pairs."""
    if isinstance(pad, Tensor):
        pad = pad.numpy().tolist()  # noqa: PTA002 -- pad widths are static shape arguments in XLA
    pad = [_int(p) for p in pad]
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]

    def impl(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            nspatial = len(pad) // 2
            widths = [(0, 0)] * nd
            # paddle packs trailing spatial dims in reverse (W first)
            if data_format.startswith("NC"):
                spatial = list(range(2, nd))
            else:
                spatial = list(range(1, nd - 1))
            for i in range(nspatial):
                dim = spatial[len(spatial) - 1 - i]
                widths[dim] = (pad[2 * i], pad[2 * i + 1])
        if jmode == "constant":
            return jnp.pad(a, widths, mode=jmode, constant_values=value)
        return jnp.pad(a, widths, mode=jmode)
    return apply("pad3d", impl, x)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        return apply("repeat_interleave",
                     lambda a, r: jnp.repeat(a, r, axis=axis,
                                             total_repeat_length=int(np.asarray(r._data if isinstance(r, Tensor) else r).sum())),
                     x, repeats)
    return apply("repeat_interleave", lambda a: jnp.repeat(a, repeats, axis=axis), x)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def impl(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))
    return apply("pixel_shuffle", impl, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def impl(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = a.transpose(0, 1, 3, 5, 2, 4)
        return a.reshape(n, c * r * r, h // r, w // r)
    return apply("pixel_unshuffle", impl, x)


def shuffle_channel(x, group):
    def impl(a):
        n, c, h, w = a.shape
        return a.reshape(n, group, c // group, h, w).swapaxes(1, 2).reshape(n, c, h, w)
    return apply("shuffle_channel", impl, x)


def space_to_depth(x, blocksize, name=None):
    def impl(a):
        n, c, h, w = a.shape
        b = blocksize
        a = a.reshape(n, c, h // b, b, w // b, b)
        a = a.transpose(0, 3, 5, 1, 2, 4)
        return a.reshape(n, c * b * b, h // b, w // b)
    return apply("space_to_depth", impl, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: operators/unfold_op.cc)."""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]

    def impl(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (pd[0], pd[1]), (pd[2], pd[3])])
        oh = (a.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (a.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                di, dj = i * dl[0], j * dl[1]
                patches.append(a[:, :, di:di + oh * st[0]:st[0], dj:dj + ow * st[1]:st[1]])
        out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)
    return apply("unfold", impl, x)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """reference: operators/shard_index_op.cc (model-parallel embedding helper)."""
    def impl(i):
        shard_size = (index_num + nshards - 1) // nshards
        lo, hi = shard_id * shard_size, (shard_id + 1) * shard_size
        in_range = (i >= lo) & (i < hi)
        return jnp.where(in_range, i - lo, ignore_value)
    return apply("shard_index", impl, input)


def cast(x, dtype):
    return x.astype(dtype)


def as_complex(x, name=None):
    return apply("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def as_real(x, name=None):
    return apply("as_real", lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], -1), x)


def tensordot(x, y, axes=2, name=None):
    return apply("tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def einsum(equation, *operands):
    return apply("einsum", lambda *ops: jnp.einsum(equation, *ops), *operands)


def tolist(x):
    return x.numpy().tolist()  # noqa: PTA002 -- tolist() IS the materialization API; host transfer is the contract
