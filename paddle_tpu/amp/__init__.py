"""paddle.amp: automatic mixed precision.

Reference: python/paddle/amp/auto_cast.py:20, grad_scaler.py:20 →
fluid/dygraph/amp/auto_cast.py:91 amp_guard + loss_scaler.py:27 AmpScaler,
C++ white/black lists imperative/amp_auto_cast.h:31, and the AMP ops
check_finite_and_unscale / update_loss_scaling (operators/amp/).

TPU design: the preferred low dtype is bfloat16 (MXU native, same exponent
range as fp32 ⇒ loss scaling is a no-op kept for API parity); float16 is
supported with real dynamic loss scaling for parity with ported scripts. The
autocast hook lives in the op-dispatch funnel (ops/dispatch.py), exactly
where the reference tracer casts inputs (imperative/tracer.cc:162).
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtypes as _dt
from ..core import monitor as _monitor
from ..ops.dispatch import register_amp_handler, apply_raw

# reference: imperative/amp_auto_cast.cc default lists
WHITE_LIST = {
    "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose", "matmul_v2", "bmm", "mm", "mv", "linear", "mul",
    "einsum", "addmm",
}
BLACK_LIST = {
    "exp", "square", "log", "log2", "log10", "log1p", "reduce_mean",
    "reduce_sum", "logsumexp", "mean", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "bce_loss", "nll_loss",
    "cross_entropy", "p_norm", "dist", "squared_l2_norm", "cumsum",
    "mse_loss", "l1_loss", "kldiv_loss", "softmax", "log_softmax",
}
# Normalization ops compute their statistics in f32 internally
# (nn/functional/norm.py _stat_dtype), so under bf16 they are dtype-NEUTRAL:
# bf16 activations flow straight through without the f32 up/down-cast
# ping-pong that doubles conv→bn HBM traffic (the reference keeps bn fp32
# because fp16 statistics overflow — fp16 keeps that behavior here).
NORM_OPS = {"layer_norm", "batch_norm", "instance_norm", "group_norm",
            "norm"}

_STATE = {"enabled": False, "dtype": None, "level": "O1",
          "custom_white": set(), "custom_black": set()}


def _amp_hook(op_name: str, tensors: List[Tensor]) -> List[Tensor]:
    if not _STATE["enabled"]:
        return tensors
    low = _STATE["dtype"]
    white = (WHITE_LIST | _STATE["custom_white"]) - _STATE["custom_black"]
    black = BLACK_LIST | _STATE["custom_black"]
    if np.dtype(low) == np.dtype("float16"):
        black = black | NORM_OPS
    elif op_name in NORM_OPS and op_name not in _STATE["custom_black"]:
        return tensors  # bf16-neutral: f32 stats happen inside the op
    if _STATE["level"] == "O2":
        cast_low = op_name not in black
    else:
        cast_low = op_name in white
    out = []
    for t in tensors:
        if _dt.is_floating(t.dtype):
            if cast_low and t.dtype != low and t.dtype != np.dtype("float64"):
                out.append(_cast_keep_graph(t, low))
                continue
            if (not cast_low and op_name in black
                    and t.dtype == np.dtype(low)):
                out.append(_cast_keep_graph(t, np.float32))
                continue
        out.append(t)
    return out


def _cast_keep_graph(t: Tensor, dtype):
    # cast through the dispatch funnel so grads flow (cast has a vjp)
    d = np.dtype(dtype)
    from ..ops.dispatch import apply
    prev = _STATE["enabled"]
    _STATE["enabled"] = False  # avoid recursive autocast of the cast op
    try:
        return apply("amp_cast", lambda x: x.astype(d), t)
    finally:
        _STATE["enabled"] = prev


register_amp_handler(_amp_hook)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """reference: amp/auto_cast.py:20 (dtype default here is bf16 — the TPU
    native low precision; pass 'float16' for parity experiments)."""
    prev = dict(_STATE)
    _STATE["enabled"] = bool(enable)
    _STATE["dtype"] = _dt.convert_dtype(dtype)
    _STATE["level"] = level
    _STATE["custom_white"] = set(custom_white_list or ())
    _STATE["custom_black"] = set(custom_black_list or ())
    try:
        yield
    finally:
        _STATE.update(prev)


amp_guard = auto_cast


def enable_operator_amp(level="O1", dtype="bfloat16", custom_white_list=None,
                        custom_black_list=None):
    """Globally enable per-op auto-cast without a context manager — the
    fleet-strategy path (reference: the AMP meta-optimizer makes the whole
    program mixed-precision rather than a scoped region)."""
    _STATE["enabled"] = True
    _STATE["dtype"] = _dt.convert_dtype(dtype)
    _STATE["level"] = level
    _STATE["custom_white"] = set(custom_white_list or ())
    _STATE["custom_black"] = set(custom_black_list or ())


def disable_operator_amp():
    _STATE["enabled"] = False


def is_auto_cast_enabled():
    return _STATE["enabled"]


def get_amp_dtype():
    return _STATE["dtype"]


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """reference: amp/auto_cast.py decorate (O2 casts model params to the low
    dtype; optimizers keep fp32 master weights via multi_precision)."""
    low = _dt.convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m._cast_to(low)
            m._casted_by_pure_fp16 = True
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


import functools
import jax


@jax.jit
def _fused_unscale(grads, scale):
    """check_finite_and_unscale as one XLA program (reference:
    operators/amp/check_finite_and_unscale_op). ``scale`` is traced so
    dynamic loss-scale changes don't recompile."""
    inv = 1.0 / scale
    out = tuple(g * inv.astype(g.dtype) for g in grads)
    finite = jnp.stack([jnp.all(jnp.isfinite(g)) for g in out])
    return out, ~jnp.all(finite)


class GradScaler:
    """Dynamic loss scaling (reference: amp/grad_scaler.py:20 →
    fluid/dygraph/amp/loss_scaler.py:27 AmpScaler; kernels
    check_finite_and_unscale + update_loss_scaling as one fused check here).

    With bf16 (TPU default) scaling is mathematically unnecessary; the class
    still tracks found_inf so ported fp16 scripts behave identically."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # per-optimizer UNSCALED state (reference: grad_scaler.py caches an
        # OptState per optimizer) so multi-optimizer recipes can't
        # double-unscale or step with still-scaled grads
        self._unscaled_ids = set()

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        if id(optimizer) in self._unscaled_ids:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer since "
                "the last update()")
        # one fused program: unscale every grad and reduce a single
        # found_inf flag — a single host sync instead of O(n_params)
        # device round-trips (reference fuses this the same way in the
        # check_finite_and_unscale kernel, operators/amp/)
        grads = [p._grad for p in optimizer._parameter_list
                 if p._grad is not None]
        if grads:
            new_grads, found = _fused_unscale(
                tuple(grads), jnp.asarray(self._scale, jnp.float32))
            it = iter(new_grads)
            for p in optimizer._parameter_list:
                if p._grad is not None:
                    p._grad = next(it)
            if bool(found):
                self._found_inf = True
                _monitor.stat_add("amp.found_inf_steps", 1)
        self._unscaled_ids.add(id(optimizer))

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if id(optimizer) not in self._unscaled_ids:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        self._unscaled_ids.clear()
        if not (self._enable and self._dynamic):
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        _monitor.stat_set("amp.loss_scale", self._scale)

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        # emits both this repo's historical keys (good_steps/bad_steps) and
        # the reference AmpScaler's (incr_count/decr_count, grad_scaler.py),
        # so checkpoints round-trip with ported scripts in either direction
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n,
                "decr_every_n_nan_or_inf": self._decr_every_n,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps,
                "incr_count": self._good_steps,
                "decr_count": self._bad_steps,
                "use_dynamic_loss_scaling": self._dynamic,
                "found_inf": self._found_inf}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._incr_ratio = state.get("incr_ratio", self._incr_ratio)
        self._decr_ratio = state.get("decr_ratio", self._decr_ratio)
        self._incr_every_n = state.get("incr_every_n_steps",
                                       self._incr_every_n)
        self._decr_every_n = state.get("decr_every_n_nan_or_inf",
                                       self._decr_every_n)
        self._good_steps = int(state.get(
            "good_steps", state.get("incr_count", self._good_steps)))
        self._bad_steps = int(state.get(
            "bad_steps", state.get("decr_count", self._bad_steps)))
        self._dynamic = bool(state.get("use_dynamic_loss_scaling",
                                       self._dynamic))
        self._found_inf = bool(state.get("found_inf", False))


AmpScaler = GradScaler
