"""paddle.autograd: user-facing autograd utilities — PyLayer (user-defined
differentiable ops) and the functional grad/backward surface.

Reference: python/paddle/autograd/ (PyLayer in py_layer.py backed by
imperative/py_layer_fwd.h; paddle.autograd.backward/grad). TPU design: a
PyLayer becomes one tape GradNode whose vjp calls the user's ``backward``
staticmethod; because the backward itself executes through the op funnel
when invoked with differentiable cotangents, double grad through a PyLayer
composes for free (reference: partial_grad_engine.cc handles this with a
dedicated engine).
"""
from __future__ import annotations

from typing import List

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import autograd_engine as _ag
from ..core.autograd_engine import grad, backward, no_grad  # noqa: F401


class PyLayerContext:
    """reference: py_layer.py PyLayerContext (save_for_backward /
    saved_tensor; ``container`` kept for API parity)."""

    def __init__(self):
        self.container = None
        self._saved: List[Tensor] = []
        self._non_differentiable = set()

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return list(self._saved)

    def mark_non_differentiable(self, *tensors):
        self._non_differentiable |= {id(t) for t in tensors}


class _PyLayerNode(_ag.GradNode):
    __slots__ = ("cls", "ctx", "single_out")

    def __init__(self, cls, ctx, inputs, outs, single_out):
        self.cls = cls
        self.ctx = ctx
        self.single_out = single_out
        out_avals = [(tuple(o._data.shape), o._data.dtype) for o in outs]
        super().__init__(cls.__name__, self._vjp, inputs, out_avals,
                         replay=None)

    def _wrap_cots(self, cot_tuple):
        import jax
        cts = []
        for (shape, dtype), c in zip(self.out_avals, cot_tuple):
            if isinstance(c, Tensor):
                cts.append(c)
            elif getattr(c, "dtype", None) == jax.dtypes.float0:
                cts.append(Tensor(jnp.zeros(shape, jnp.float32)))
            else:
                cts.append(Tensor(c))
        return cts

    def _call_backward(self, cts):
        gs = self.cls.backward(self.ctx, *(cts if not self.single_out
                                           else cts[:1]))
        gs = gs if isinstance(gs, (list, tuple)) else (gs,)
        if len(gs) != len(self.inputs):
            raise RuntimeError(
                f"{self.cls.__name__}.backward returned {len(gs)} grads "
                f"for {len(self.inputs)} tensor inputs")
        return list(gs)

    def _vjp(self, cot_tuple):
        with _ag.no_grad():
            gs = self._call_backward(self._wrap_cots(cot_tuple))
        out = []
        for g, ref in zip(gs, self.inputs):
            if g is None:
                out.append(jnp.zeros(ref.tensor._data.shape,
                                     ref.tensor._data.dtype))
            else:
                out.append(g._data if isinstance(g, Tensor)
                           else jnp.asarray(g))
        return tuple(out)

    def py_replay(self):
        """Double-grad path: run the user backward with grad-tracked
        cotangents so its ops record their own tape."""
        cts = self._wrap_cots(self.cotangents())
        gs = self._call_backward(cts)
        out = []
        for g, ref in zip(gs, self.inputs):
            if g is None:
                out.append(Tensor(jnp.zeros(ref.tensor._data.shape,
                                            ref.tensor._data.dtype)))
            else:
                out.append(g if isinstance(g, Tensor) else Tensor(g))
        return out


class PyLayer:
    """User-defined differentiable op (reference: paddle.autograd.PyLayer,
    imperative/py_layer_fwd.h).

    Subclass with ``forward(ctx, *args)`` and ``backward(ctx, *grads)``
    staticmethods; call via ``.apply(*args)``.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with _ag.no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(out, (list, tuple))
        outs = [out] if single else list(out)
        outs = [o if isinstance(o, Tensor) else Tensor(o) for o in outs]
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        need = (_ag.is_grad_enabled()
                and any(not t.stop_gradient for t in tensor_inputs))
        if need:
            node = _PyLayerNode(cls, ctx, tensor_inputs, outs, single)
            bound = []
            for i, o in enumerate(outs):
                differentiable = (id(o) not in ctx._non_differentiable
                                  and _ag._is_inexact(o._data.dtype))
                t = Tensor(o._data, stop_gradient=not differentiable)
                if differentiable:
                    t._grad_node = (node, i)
                bound.append(t)
            outs = bound
        return outs[0] if single else tuple(outs)


PyLayerMeta = type  # API-parity alias (reference exposes a metaclass)


# -- reference autograd/backward_mode.py ------------------------------------

def backward(tensors, grad_tensors=None, retain_graph=False):
    """reference: autograd/backward_mode.py backward — run backward on a
    list of output tensors with optional cotangents."""
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    grad_tensors = (grad_tensors if isinstance(grad_tensors, (list, tuple))
                    else [grad_tensors])
    if len(grad_tensors) != len(tensors):
        raise ValueError("backward: tensors and grad_tensors length "
                         "mismatch")
    for t, g in zip(tensors, grad_tensors):
        t.backward(g, retain_graph=retain_graph)


import sys as _sys
backward_mode = _sys.modules[__name__]
