"""Image file IO ops (reference: operators/read_file_op.cc,
operators/decode_jpeg_op.cc — the reference decodes via nvjpeg on GPU;
host-side PIL/numpy is the TPU-era equivalent since decode feeds the input
pipeline, not the accelerator).
"""
from __future__ import annotations

import io as _io

import numpy as np

from ..core.tensor import Tensor
from ..ops.creation import to_tensor

__all__ = ["image_load", "image_decode", "read_file", "decode_jpeg"]


def read_file(path, name=None):
    """reference: read_file_op — file bytes as a uint8 tensor."""
    with open(path, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return to_tensor(data)


def decode_jpeg(x, mode="unchanged", name=None):
    """reference: decode_jpeg_op — decode an encoded image byte tensor to
    CHW uint8. ``mode``: unchanged | gray | rgb."""
    from PIL import Image
    raw = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
    img = Image.open(_io.BytesIO(raw.tobytes()))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return to_tensor(np.ascontiguousarray(arr))


def image_load(path, backend=None):
    """reference: vision/image.py image_load — HWC image (numpy backend)."""
    from PIL import Image
    with Image.open(path) as img:
        return np.asarray(img.convert("RGB"))


def image_decode(x, mode="rgb"):
    """Alias of :func:`decode_jpeg` under the vision namespace."""
    return decode_jpeg(x, mode=mode)
