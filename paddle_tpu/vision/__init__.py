"""paddle.vision parity namespace (reference: python/paddle/vision/)."""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from .models import (  # noqa: F401
    LeNet, ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
    VGG, vgg11, vgg13, vgg16, vgg19,
    MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2,
    mobilenet_v3_large, mobilenet_v3_small,
)
from .image import (image_load, image_decode, read_file,  # noqa: F401
                    decode_jpeg)


def set_image_backend(backend):
    """reference: vision/image.py set_image_backend — numpy is the only
    backend here (cv2/PIL both feed numpy arrays)."""
    if backend not in ("pil", "cv2", "numpy", "tensor"):
        raise ValueError(f"unknown image backend {backend}")


def get_image_backend():
    return "numpy"
