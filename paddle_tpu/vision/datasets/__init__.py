"""Vision datasets (reference: python/paddle/vision/datasets/ — mnist.py,
cifar.py, folder.py). Zero-egress environment: ``download=True`` raises with
instructions instead of fetching; file parsing matches the reference formats
(IDX for MNIST, pickled batches for CIFAR, class-dirs for DatasetFolder).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, List, Optional, Tuple

import numpy as np

from ...io import Dataset


def _no_download(name):
    raise RuntimeError(
        f"{name}: automatic download is unavailable (no network egress); "
        f"pass the local file path(s) explicitly")


class MNIST(Dataset):
    """reference: vision/datasets/mnist.py MNIST (IDX ubyte files)."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        if image_path is None or label_path is None:
            _no_download(type(self).__name__)
        self.images = self._parse_images(image_path)
        self.labels = self._parse_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _parse_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad MNIST image magic {magic} in {path}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _parse_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad MNIST label magic {magic} in {path}")
            return np.frombuffer(f.read(n), dtype=np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img[None].astype(np.float32) / 255.0
        return img, np.array([label], dtype=np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    """reference: vision/datasets/mnist.py FashionMNIST (same format)."""
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """reference: vision/datasets/cifar.py Cifar10 (python-pickle batches in
    a tar.gz)."""

    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        if data_file is None:
            _no_download(type(self).__name__)
        self.data, self.labels = self._load(data_file)

    def _label_key(self):
        return b"labels"

    def _load(self, data_file):
        wanted = (self._train_members if self.mode == "train"
                  else self._test_members)
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if base in wanted:
                    batch = pickle.load(tf.extractfile(member),
                                        encoding="bytes")
                    images.append(batch[b"data"])
                    labels.extend(batch[self._label_key()])
        if not images:
            raise ValueError(f"no {self.mode} batches found in {data_file}")
        data = np.concatenate(images).reshape(-1, 3, 32, 32)
        return data.transpose(0, 2, 3, 1), np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.transpose(2, 0, 1).astype(np.float32) / 255.0
        return img, np.array([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    """reference: vision/datasets/cifar.py Cifar100."""
    _train_members = ["train"]
    _test_members = ["test"]

    def _label_key(self):
        return b"fine_labels"


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image
        with open(path, "rb") as f:
            return np.asarray(Image.open(f).convert("RGB"))
    except ImportError as e:
        raise RuntimeError(
            f"loading {path} needs PIL; save images as .npy arrays or "
            f"provide a custom loader") from e


class DatasetFolder(Dataset):
    """reference: vision/datasets/folder.py DatasetFolder (class-per-dir)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(e.name for e in os.scandir(root) if e.is_dir())
        if not classes:
            raise ValueError(f"no class directories found in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: List[Tuple[str, int]] = []
        for c in classes:
            for dirpath, _, files in sorted(os.walk(os.path.join(root, c))):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = (is_valid_file(path) if is_valid_file is not None
                          else fname.lower().endswith(extensions))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """reference: vision/datasets/folder.py ImageFolder (flat, no labels)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or IMG_EXTENSIONS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = (is_valid_file(path) if is_valid_file is not None
                      else fname.lower().endswith(extensions))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise ValueError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class VOC2012(Dataset):
    """reference: vision/datasets/voc2012.py VOC2012 — segmentation pairs
    straight out of the VOCtrainval tar (JPEGImages/*.jpg +
    SegmentationClass/*.png, split lists under ImageSets/Segmentation).

    Zero-egress environment: pass ``data_file`` (the
    VOCtrainval_11-May-2012.tar path) explicitly. The reference's mode
    quirk is kept for parity: 'train' reads the trainval list and 'test'
    reads the train list (voc2012.py MODE_FLAG_MAP).
    """

    SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
    MODE_FLAG_MAP = {"train": "trainval", "test": "train", "valid": "val"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        mode = mode.lower()
        if mode not in self.MODE_FLAG_MAP:
            raise ValueError(f"mode should be 'train', 'valid' or 'test', "
                             f"but got {mode}")
        if data_file is None:
            _no_download(type(self).__name__)
        self.transform = transform
        self.flag = self.MODE_FLAG_MAP[mode]
        self.data_tar = tarfile.open(data_file)
        self.name2mem = {m.name: m for m in self.data_tar.getmembers()}
        set_member = self.name2mem[self.SET_FILE.format(self.flag)]
        self.data, self.labels = [], []
        for line in self.data_tar.extractfile(set_member):
            name = line.strip().decode("utf-8")
            if not name:
                continue
            self.data.append(self.DATA_FILE.format(name))
            self.labels.append(self.LABEL_FILE.format(name))

    def _decode(self, member_name):
        import io as _io
        raw = self.data_tar.extractfile(self.name2mem[member_name]).read()
        if member_name.endswith(".npy"):
            return np.load(_io.BytesIO(raw))
        from PIL import Image
        return np.array(Image.open(_io.BytesIO(raw)))

    def __getitem__(self, idx):
        img = self._decode(self.data[idx])
        label = self._decode(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.data)


class Flowers(Dataset):
    """reference: vision/datasets/flowers.py Flowers — 102-category Oxford
    flowers: images in a .tgz, labels + split indices in MATLAB .mat files.

    Zero-egress environment: pass ``data_file``/``label_file``/
    ``setid_file`` explicitly. The reference's train/test swap is kept for
    parity ('train' uses the official tstid split because it is larger).
    """

    MODE_FLAG_MAP = {"train": "tstid", "test": "trnid", "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        mode = mode.lower()
        if mode not in self.MODE_FLAG_MAP:
            raise ValueError(f"mode should be 'train', 'valid' or 'test', "
                             f"but got {mode}")
        if data_file is None or label_file is None or setid_file is None:
            _no_download(type(self).__name__)
        self.transform = transform
        import scipy.io as scio
        self.labels = scio.loadmat(label_file)["labels"][0]
        self.indexes = scio.loadmat(setid_file)[self.MODE_FLAG_MAP[mode]][0]
        # extract once next to the archive, like the reference
        self.data_path = data_file
        for suffix in (".tgz", ".tar.gz", ".tar"):
            if data_file.endswith(suffix):
                self.data_path = data_file[:-len(suffix)] + "/"
                break
        if self.data_path != data_file and not os.path.exists(
                os.path.join(self.data_path, ".extracted")):
            os.makedirs(self.data_path, exist_ok=True)
            with tarfile.open(data_file) as tf:
                tf.extractall(self.data_path, filter="data")
            with open(os.path.join(self.data_path, ".extracted"), "w"):
                pass    # sentinel: skip re-extraction next construction

    def __getitem__(self, idx):
        index = int(self.indexes[idx])
        label = np.array([self.labels[index - 1]], np.int64)
        for ext in ("jpg/image_%05d.jpg", "jpg/image_%05d.npy"):
            path = os.path.join(self.data_path, ext % index)
            if os.path.exists(path):
                break
        image = _default_loader(path)
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self.indexes)


VOC_CLASSES = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor")


class VOCDetection(Dataset):
    """Pascal-VOC *detection* annotations out of a VOCdevkit directory —
    the ingest side of the YOLOv3 workload (reference capability:
    PaddleDetection's VOCDataSet feeding
    fluid/operators/detection/yolov3_loss_op.cc; the base repo ships only
    the segmentation reader, voc2012.py).

    Returns ``(image HWC uint8, gt_box [M, 4] float32 xyxy pixels,
    gt_label [M] int64, difficult [M] int64)`` per sample. Samples with
    zero boxes are kept (empty arrays) — padding to fixed M is the
    transform/collate layer's job (static shapes for the TPU).
    """

    def __init__(self, root, year="2012", mode="train", transform=None,
                 classes=None, keep_difficult=True, image_set=None):
        self.root = root
        self.transform = transform
        self.keep_difficult = keep_difficult
        classes = classes or VOC_CLASSES
        self.classes = tuple(classes)
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        base = os.path.join(root, f"VOC{year}")
        if not os.path.isdir(base):
            base = root     # already pointing inside VOCdevkit/VOCxxxx
        self._img_dir = os.path.join(base, "JPEGImages")
        self._ann_dir = os.path.join(base, "Annotations")
        set_file = os.path.join(base, "ImageSets", "Main",
                                (image_set or mode) + ".txt")
        if os.path.exists(set_file):
            with open(set_file) as f:
                self.ids = [l.split()[0] for l in f if l.strip()]
        else:                           # no split list: every annotation
            self.ids = sorted(os.path.splitext(f)[0]
                              for f in os.listdir(self._ann_dir)
                              if f.endswith(".xml"))
        if not self.ids:
            raise ValueError(f"VOCDetection: no samples under {base}")

    def _parse_ann(self, xml_path):
        import xml.etree.ElementTree as ET
        rootel = ET.parse(xml_path).getroot()
        boxes, labels, difficult = [], [], []
        for obj in rootel.iter("object"):
            name = obj.find("name").text.strip().lower()
            if name not in self.class_to_idx:
                continue
            diff = int((obj.find("difficult").text or 0)
                       if obj.find("difficult") is not None else 0)
            if diff and not self.keep_difficult:
                continue
            bb = obj.find("bndbox")
            # VOC pixel indices are 1-based inclusive
            box = [float(bb.find(k).text) - 1.0
                   for k in ("xmin", "ymin", "xmax", "ymax")]
            boxes.append(box)
            labels.append(self.class_to_idx[name])
            difficult.append(diff)
        return (np.asarray(boxes, np.float32).reshape(-1, 4),
                np.asarray(labels, np.int64),
                np.asarray(difficult, np.int64))

    def __getitem__(self, idx):
        name = self.ids[idx]
        for ext in (".jpg", ".npy", ".png"):
            p = os.path.join(self._img_dir, name + ext)
            if os.path.exists(p):
                break
        img = _default_loader(p)
        boxes, labels, difficult = self._parse_ann(
            os.path.join(self._ann_dir, name + ".xml"))
        sample = (img, boxes, labels, difficult)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample

    def __len__(self):
        return len(self.ids)


# reference exposes per-dataset submodules (vision/datasets/mnist.py etc.);
# here one module defines them all — alias the names for import parity
import sys as _sys
mnist = cifar = folder = voc2012 = flowers = _sys.modules[__name__]
