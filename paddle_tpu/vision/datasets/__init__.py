"""Vision datasets (reference: python/paddle/vision/datasets/ — mnist.py,
cifar.py, folder.py). Zero-egress environment: ``download=True`` raises with
instructions instead of fetching; file parsing matches the reference formats
(IDX for MNIST, pickled batches for CIFAR, class-dirs for DatasetFolder).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, List, Optional, Tuple

import numpy as np

from ...io import Dataset


def _no_download(name):
    raise RuntimeError(
        f"{name}: automatic download is unavailable (no network egress); "
        f"pass the local file path(s) explicitly")


class MNIST(Dataset):
    """reference: vision/datasets/mnist.py MNIST (IDX ubyte files)."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        if image_path is None or label_path is None:
            _no_download(type(self).__name__)
        self.images = self._parse_images(image_path)
        self.labels = self._parse_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _parse_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad MNIST image magic {magic} in {path}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _parse_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad MNIST label magic {magic} in {path}")
            return np.frombuffer(f.read(n), dtype=np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img[None].astype(np.float32) / 255.0
        return img, np.array([label], dtype=np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    """reference: vision/datasets/mnist.py FashionMNIST (same format)."""
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """reference: vision/datasets/cifar.py Cifar10 (python-pickle batches in
    a tar.gz)."""

    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        if data_file is None:
            _no_download(type(self).__name__)
        self.data, self.labels = self._load(data_file)

    def _label_key(self):
        return b"labels"

    def _load(self, data_file):
        wanted = (self._train_members if self.mode == "train"
                  else self._test_members)
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if base in wanted:
                    batch = pickle.load(tf.extractfile(member),
                                        encoding="bytes")
                    images.append(batch[b"data"])
                    labels.extend(batch[self._label_key()])
        if not images:
            raise ValueError(f"no {self.mode} batches found in {data_file}")
        data = np.concatenate(images).reshape(-1, 3, 32, 32)
        return data.transpose(0, 2, 3, 1), np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.transpose(2, 0, 1).astype(np.float32) / 255.0
        return img, np.array([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    """reference: vision/datasets/cifar.py Cifar100."""
    _train_members = ["train"]
    _test_members = ["test"]

    def _label_key(self):
        return b"fine_labels"


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image
        with open(path, "rb") as f:
            return np.asarray(Image.open(f).convert("RGB"))
    except ImportError as e:
        raise RuntimeError(
            f"loading {path} needs PIL; save images as .npy arrays or "
            f"provide a custom loader") from e


class DatasetFolder(Dataset):
    """reference: vision/datasets/folder.py DatasetFolder (class-per-dir)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(e.name for e in os.scandir(root) if e.is_dir())
        if not classes:
            raise ValueError(f"no class directories found in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: List[Tuple[str, int]] = []
        for c in classes:
            for dirpath, _, files in sorted(os.walk(os.path.join(root, c))):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = (is_valid_file(path) if is_valid_file is not None
                          else fname.lower().endswith(extensions))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """reference: vision/datasets/folder.py ImageFolder (flat, no labels)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or IMG_EXTENSIONS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = (is_valid_file(path) if is_valid_file is not None
                      else fname.lower().endswith(extensions))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise ValueError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
