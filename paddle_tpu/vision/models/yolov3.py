"""YOLOv3 detector — BASELINE workload 4 (PaddleDetection YOLOv3/PP-YOLO
over the base repo's fluid/operators/detection/yolov3_loss_op.cc,
yolo_box_op.cc, multiclass_nms_op.cc).

TPU-first design:

- **Static shapes via size buckets.** The reference trains YOLO with
  per-step random input sizes; under XLA each distinct shape is its own
  compiled program, so multi-scale training here is a SMALL set of square
  size buckets (default 320/416/608). Each bucket compiles once and is
  reused; ``YOLOv3.train_step`` keys its jit cache on the input shape.
- **Fixed box slots.** gt boxes are zero-padded to ``num_max_boxes``
  (vision/transforms/det_transforms.py PadBox), w==h==0 marks an empty
  slot — no ragged tensors anywhere.
- **Loss on-device, decode+NMS at the edge.** The three-scale
  yolov3_loss sum is one fused jit region; eval-time decode runs
  yolo_box per scale + one multiclass_nms (Pallas/NMS under
  ops/detection.py) with fixed keep_top_k output.
"""
from __future__ import annotations

import numpy as np

from ...nn.layer_base import Layer
from ...nn import Conv2D, BatchNorm2D, LeakyReLU, Upsample
from ... import ops
from ...core.tensor import Tensor
from .darknet import ConvBNLayer, DarkNet

__all__ = ["YOLOv3", "yolov3_darknet53"]

# COCO anchor table (YOLOv3 paper); PaddleDetection yolov3 defaults
DEFAULT_ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119,
                   116, 90, 156, 198, 373, 326]
DEFAULT_ANCHOR_MASKS = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]


class YoloDetBlock(Layer):
    """Five alternating 1x1/3x3 convs + the 3x3 'tip' (YOLOv3 fig. 3)."""

    def __init__(self, in_ch, channel):
        super().__init__()
        self.conv0 = ConvBNLayer(in_ch, channel, kernel=1)
        self.conv1 = ConvBNLayer(channel, channel * 2, kernel=3)
        self.conv2 = ConvBNLayer(channel * 2, channel, kernel=1)
        self.conv3 = ConvBNLayer(channel, channel * 2, kernel=3)
        self.route = ConvBNLayer(channel * 2, channel, kernel=1)
        self.tip = ConvBNLayer(channel, channel * 2, kernel=3)

    def forward(self, x):
        r = self.route(self.conv3(self.conv2(self.conv1(self.conv0(x)))))
        return r, self.tip(r)


class YOLOv3(Layer):
    """Backbone + 3-scale FPN head + raw per-scale outputs.

    forward(img [N,3,S,S]) -> [out_32, out_16, out_8], each
    [N, A*(5+C), S/ds, S/ds]. ``loss``/``decode`` wrap the detection ops.
    """

    def __init__(self, num_classes=80, backbone=None, anchors=None,
                 anchor_masks=None, ignore_thresh=0.7, width_mult=1.0,
                 num_max_boxes=50):
        super().__init__()
        self.num_classes = int(num_classes)
        self.anchors = list(anchors or DEFAULT_ANCHORS)
        self.anchor_masks = [list(m) for m in
                             (anchor_masks or DEFAULT_ANCHOR_MASKS)]
        self.ignore_thresh = float(ignore_thresh)
        self.num_max_boxes = int(num_max_boxes)
        self.backbone = backbone or DarkNet(depth=53, width_mult=width_mult)
        self.downsamples = [32, 16, 8]

        in_chs = list(reversed(self.backbone.out_channels))  # C5, C4, C3
        self.blocks, self.outs, self.routes = [], [], []
        ch = None
        for i, in_ch in enumerate(in_chs):
            channel = max(int(512 * width_mult) // (2 ** i), 8)
            total_in = in_ch + (ch if i else 0)
            block = YoloDetBlock(total_in, channel)
            a = len(self.anchor_masks[i])
            out_conv = Conv2D(channel * 2, a * (5 + self.num_classes), 1)
            self.add_sublayer(f"yolo_block{i}", block)
            self.add_sublayer(f"yolo_out{i}", out_conv)
            self.blocks.append(block)
            self.outs.append(out_conv)
            if i < len(in_chs) - 1:
                route = ConvBNLayer(channel, channel // 2, kernel=1)
                self.add_sublayer(f"route{i}", route)
                self.routes.append(route)
                ch = channel // 2
        self.upsample = Upsample(scale_factor=2, mode="nearest")

    def forward(self, x):
        feats = self.backbone(x)            # [C3, C4, C5]
        outs = []
        route = None
        for i, feat in enumerate(reversed(feats)):   # C5 -> C3
            if i:
                feat = ops.concat([route, feat], axis=1)
            r, tip = self.blocks[i](feat)
            outs.append(self.outs[i](tip))
            if i < len(self.blocks) - 1:
                route = self.upsample(self.routes[i](r))
        return outs

    # -- training ---------------------------------------------------------
    def loss(self, outputs, gt_box, gt_label, gt_score=None):
        """Sum of the three per-scale yolov3_loss terms, meaned over the
        batch (reference: yolov3_loss_op.cc per scale + model-side sum)."""
        total = None
        for out, mask, ds in zip(outputs, self.anchor_masks,
                                 self.downsamples):
            l = ops.yolov3_loss(
                out, gt_box, gt_label, anchors=self.anchors,
                anchor_mask=mask, class_num=self.num_classes,
                ignore_thresh=self.ignore_thresh, downsample_ratio=ds,
                gt_score=gt_score)
            l = ops.mean(l)
            total = l if total is None else total + l
        return total

    # -- inference --------------------------------------------------------
    def decode(self, outputs, img_size, conf_thresh=0.01, nms_thresh=0.45,
               keep_top_k=100, nms_top_k=400):
        """yolo_box per scale + one multiclass NMS. Returns (dets
        [N, keep_top_k, 6] rows (label, score, x1, y1, x2, y2) padded with
        label -1, counts [N])."""
        boxes, scores = [], []
        for out, mask, ds in zip(outputs, self.anchor_masks,
                                 self.downsamples):
            anchors = []
            for i in mask:
                anchors += [self.anchors[2 * i], self.anchors[2 * i + 1]]
            b, s = ops.yolo_box(out, img_size, anchors=anchors,
                                class_num=self.num_classes,
                                conf_thresh=conf_thresh,
                                downsample_ratio=ds)
            boxes.append(b)
            scores.append(ops.transpose(s, [0, 2, 1]))
        all_boxes = ops.concat(boxes, axis=1)        # [N, M, 4]
        all_scores = ops.concat(scores, axis=2)      # [N, C, M]
        return ops.multiclass_nms(
            all_boxes, all_scores, score_threshold=conf_thresh,
            nms_top_k=nms_top_k, keep_top_k=keep_top_k,
            nms_threshold=nms_thresh, background_label=-1)


class YOLOv3Loss(Layer):
    """hapi-compatible loss head: ``loss(out32, out16, out8, gt_box,
    gt_label)``. Plugs YOLOv3 into hapi Model.prepare()/train_batch — the
    compiled-step cache there keys on input shape, so size-bucketed
    multi-scale training compiles one program per bucket and reuses it
    (the TPU answer to the reference's per-step random resize)."""

    def __init__(self, model: "YOLOv3"):
        super().__init__()
        self._cfg = dict(
            anchors=model.anchors, anchor_masks=model.anchor_masks,
            num_classes=model.num_classes,
            ignore_thresh=model.ignore_thresh,
            downsamples=model.downsamples)

    def forward(self, out32, out16, out8, gt_box, gt_label):
        c = self._cfg
        total = None
        for out, mask, ds in zip([out32, out16, out8], c["anchor_masks"],
                                 c["downsamples"]):
            l = ops.mean(ops.yolov3_loss(
                out, gt_box, gt_label, anchors=c["anchors"],
                anchor_mask=mask, class_num=c["num_classes"],
                ignore_thresh=c["ignore_thresh"], downsample_ratio=ds))
            total = l if total is None else total + l
        return total


def yolov3_darknet53(num_classes=80, pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("yolov3_darknet53: no bundled weights in this "
                         "environment; pass pretrained=<path> via "
                         "framework_io.load instead")
    return YOLOv3(num_classes=num_classes, **kwargs)
