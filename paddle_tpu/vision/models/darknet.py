"""DarkNet-53 backbone for the YOLOv3 workload.

Reference capability: PaddleDetection's darknet backbone feeding the base
repo's detection op stack (fluid/operators/detection/yolov3_loss_op.cc,
yolo_box_op.cc); the base repo's vision package ships no detection
backbone, so this fills BASELINE workload 4's model side.

TPU notes: plain Conv2D+BatchNorm2D+LeakyReLU composition in NCHW — XLA
fuses conv+bn+activation; all convs are 1x1/3x3 with static shapes so the
MXU tiles them directly. ``width_mult`` scales every channel count for
CPU-sized test configs without changing the topology.
"""
from __future__ import annotations

from ...nn.layer_base import Layer
from ...nn import Conv2D, BatchNorm2D, LeakyReLU, Sequential

__all__ = ["ConvBNLayer", "DarkNet", "darknet53"]


class ConvBNLayer(Layer):
    def __init__(self, in_ch, out_ch, kernel=3, stride=1, padding=None):
        super().__init__()
        if padding is None:
            padding = (kernel - 1) // 2
        self.conv = Conv2D(in_ch, out_ch, kernel, stride=stride,
                           padding=padding, bias_attr=False)
        self.bn = BatchNorm2D(out_ch)
        self.act = LeakyReLU(0.1)

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class BasicBlock(Layer):
    """1x1 squeeze + 3x3 expand with residual add (YOLOv3 paper fig. 1)."""

    def __init__(self, ch):
        super().__init__()
        self.conv1 = ConvBNLayer(ch, ch // 2, kernel=1)
        self.conv2 = ConvBNLayer(ch // 2, ch, kernel=3)

    def forward(self, x):
        return x + self.conv2(self.conv1(x))


class DarkNet(Layer):
    """53-layer config: stages of [1, 2, 8, 8, 4] residual blocks at
    channels [64, 128, 256, 512, 1024]; returns the C3/C4/C5 pyramid
    (stride 8/16/32 feature maps) the YOLO head consumes."""

    _stage_blocks = {53: [1, 2, 8, 8, 4]}

    def __init__(self, depth=53, width_mult=1.0, num_stages=5):
        super().__init__()
        if depth not in self._stage_blocks:
            raise ValueError(f"DarkNet: unsupported depth {depth}")
        blocks = self._stage_blocks[depth][:num_stages]

        def ch(c):
            return max(int(c * width_mult), 8)

        self.stem = ConvBNLayer(3, ch(32), kernel=3)
        self.stages = []
        in_ch = ch(32)
        for i, n in enumerate(blocks):
            out_ch = ch(64 * (2 ** i))
            stage = Sequential(
                ConvBNLayer(in_ch, out_ch, kernel=3, stride=2),
                *[BasicBlock(out_ch) for _ in range(n)])
            self.add_sublayer(f"stage{i}", stage)
            self.stages.append(stage)
            in_ch = out_ch
        self.out_channels = [ch(64 * (2 ** i))
                             for i in range(max(len(blocks) - 3, 0),
                                            len(blocks))]

    def forward(self, x):
        x = self.stem(x)
        feats = []
        for stage in self.stages:
            x = stage(x)
            feats.append(x)
        return feats[-3:]           # C3, C4, C5


def darknet53(width_mult=1.0, **kwargs):
    return DarkNet(depth=53, width_mult=width_mult, **kwargs)
