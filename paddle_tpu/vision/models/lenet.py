"""LeNet (reference: python/paddle/vision/models/lenet.py:21 — the PR1
MNIST-dygraph baseline config)."""
from ...nn.layer_base import Layer
from ...nn import Conv2D, ReLU, MaxPool2D, Linear, Sequential
from ... import ops


class LeNet(Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1, padding=0), ReLU(),
            MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = Sequential(
                Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, inputs):
        x = self.features(inputs)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.fc(x)
        return x
