"""Model zoo (reference: python/paddle/vision/models/__init__.py)."""
from .lenet import LeNet  # noqa: F401
from .resnet import (ResNet, BasicBlock, BottleneckBlock,  # noqa: F401
                     resnet18, resnet34, resnet50, resnet101, resnet152,
                     wide_resnet50_2, wide_resnet101_2)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenet import (MobileNetV1, MobileNetV2, MobileNetV3Large,  # noqa: F401
                        MobileNetV3Small, mobilenet_v1, mobilenet_v2,
                        mobilenet_v3_large, mobilenet_v3_small)
from .darknet import DarkNet, darknet53  # noqa: F401
from .yolov3 import YOLOv3, YOLOv3Loss, yolov3_darknet53  # noqa: F401
