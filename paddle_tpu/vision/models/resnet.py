"""ResNet family (reference: python/paddle/vision/models/resnet.py — same
depth table and block structure, NCHW layout).

TPU notes: plain Conv2D+BatchNorm2D composition — XLA fuses conv+bn+relu;
bf16 under amp.auto_cast hits the MXU at full tile width. No manual fusion.
"""
from __future__ import annotations

from ...nn.layer_base import Layer
from ...nn import (Conv2D, BatchNorm2D, ReLU, MaxPool2D, AdaptiveAvgPool2D,
                   Linear, Sequential)
from ... import ops


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or BatchNorm2D
        if groups != 1 or base_width != 64:
            raise ValueError("BasicBlock only supports groups=1, base_width=64")
        self.conv1 = Conv2D(inplanes, planes, 3, padding=1, stride=stride,
                            bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = ReLU()
        self.conv2 = Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = Conv2D(width, width, 3, padding=dilation, stride=stride,
                            groups=groups, dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = Conv2D(width, planes * self.expansion, 1, bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(Layer):
    """reference: vision/models/resnet.py ResNet (depth table :261)."""

    _depth_cfg = {
        18: (BasicBlock, [2, 2, 2, 2]),
        34: (BasicBlock, [3, 4, 6, 3]),
        50: (BottleneckBlock, [3, 4, 6, 3]),
        101: (BottleneckBlock, [3, 4, 23, 3]),
        152: (BottleneckBlock, [3, 8, 36, 3]),
    }

    def __init__(self, block=None, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1):
        super().__init__()
        if block is None:
            block, layer_cfg = self._depth_cfg[depth]
        else:
            layer_cfg = self._depth_cfg[depth][1]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.groups = groups
        self.base_width = width
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(self.inplanes)
        self.relu = ReLU()
        self.maxpool = MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layer_cfg[0])
        self.layer2 = self._make_layer(block, 128, layer_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layer_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layer_cfg[3], stride=2)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                Conv2D(self.inplanes, planes * block.expansion, 1,
                       stride=stride, bias_attr=False),
                BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes,
                                groups=self.groups, base_width=self.base_width))
        return Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.fc(x)
        return x


def _resnet(depth, pretrained=False, **kwargs):
    model = ResNet(depth=depth, **kwargs)
    if pretrained:
        from ...framework_io import convert_reference_checkpoint
        if not isinstance(pretrained, str):
            # no egress in this environment: the producer for local files
            # is tools/convert_reference_checkpoint.py (reference-format
            # .pdparams in, verified load here)
            raise RuntimeError(
                "pretrained=True needs network access; pass "
                "pretrained='/path/to/resnet.pdparams' (reference-format "
                "checkpoint — see tools/convert_reference_checkpoint.py)")
        convert_reference_checkpoint(pretrained, model)
    return model


def resnet18(pretrained=False, **kwargs):
    return _resnet(18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(152, pretrained, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(50, pretrained, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(101, pretrained, **kwargs)
