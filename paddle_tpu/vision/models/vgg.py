"""VGG family (reference: python/paddle/vision/models/vgg.py — same cfgs)."""
from ...nn.layer_base import Layer
from ...nn import (Conv2D, BatchNorm2D, ReLU, MaxPool2D, AdaptiveAvgPool2D,
                   Linear, Dropout, Sequential)
from ... import ops


class VGG(Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(512 * 7 * 7, 4096), ReLU(), Dropout(),
                Linear(4096, 4096), ReLU(), Dropout(),
                Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.classifier(x)
        return x


def make_layers(cfg, batch_norm=False):
    layers = []
    in_channels = 3
    for v in cfg:
        if v == "M":
            layers.append(MaxPool2D(kernel_size=2, stride=2))
        else:
            conv2d = Conv2D(in_channels, v, 3, padding=1)
            if batch_norm:
                layers += [conv2d, BatchNorm2D(v), ReLU()]
            else:
                layers += [conv2d, ReLU()]
            in_channels = v
    return Sequential(*layers)


cfgs = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def _vgg(cfg, batch_norm, pretrained, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights require network access")
    return VGG(make_layers(cfgs[cfg], batch_norm=batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("A", batch_norm, pretrained, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("B", batch_norm, pretrained, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("D", batch_norm, pretrained, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("E", batch_norm, pretrained, **kwargs)
