"""MobileNet V1/V2/V3 (reference: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py; V3 per the PaddleClas config named in BASELINE config 2).

Depthwise convs map to XLA's feature_group_count convolution — no special
kernels needed on TPU.
"""
from __future__ import annotations

from ...nn.layer_base import Layer
from ...nn import (Conv2D, BatchNorm2D, ReLU, ReLU6, Hardswish, Hardsigmoid,
                   AdaptiveAvgPool2D, Linear, Dropout, Sequential)
from ... import ops


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNLayer(Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, groups=1,
                 act=ReLU):
        super().__init__()
        self._conv = Conv2D(in_c, out_c, kernel, stride=stride,
                            padding=(kernel - 1) // 2, groups=groups,
                            bias_attr=False)
        self._bn = BatchNorm2D(out_c)
        self._act = act() if act is not None else None

    def forward(self, x):
        x = self._bn(self._conv(x))
        return self._act(x) if self._act is not None else x


class DepthwiseSeparable(Layer):
    """reference: mobilenetv1.py DepthwiseSeparable."""

    def __init__(self, in_c, out_c1, out_c2, num_groups, stride, scale):
        super().__init__()
        self._depthwise = ConvBNLayer(in_c, int(out_c1 * scale), 3,
                                      stride=stride,
                                      groups=int(num_groups * scale))
        self._pointwise = ConvBNLayer(int(out_c1 * scale),
                                      int(out_c2 * scale), 1)

    def forward(self, x):
        return self._pointwise(self._depthwise(x))


class MobileNetV1(Layer):
    """reference: vision/models/mobilenetv1.py MobileNetV1."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, stride=2)
        cfg = [  # in, c1, c2, groups, stride
            (32, 32, 64, 32, 1), (64, 64, 128, 64, 2),
            (128, 128, 128, 128, 1), (128, 128, 256, 128, 2),
            (256, 256, 256, 256, 1), (256, 256, 512, 256, 2),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 1024, 512, 2),
            (1024, 1024, 1024, 1024, 1),
        ]
        blocks = [DepthwiseSeparable(int(i * scale), c1, c2, g, s, scale)
                  for i, c1, c2, g, s in cfg]
        self.blocks = Sequential(*blocks)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.fc(x)
        return x


class InvertedResidual(Layer):
    """reference: mobilenetv2.py InvertedResidual."""

    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden_dim = int(round(inp * expand_ratio))
        self.use_res_connect = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(inp, hidden_dim, 1, act=ReLU6))
        layers += [
            ConvBNLayer(hidden_dim, hidden_dim, 3, stride=stride,
                        groups=hidden_dim, act=ReLU6),
            ConvBNLayer(hidden_dim, oup, 1, act=None),
        ]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res_connect else out


class MobileNetV2(Layer):
    """reference: vision/models/mobilenetv2.py MobileNetV2."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        input_channel = _make_divisible(32 * scale)
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        features = [ConvBNLayer(3, input_channel, 3, stride=2, act=ReLU6)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    input_channel, out_c, s if i == 0 else 1, t))
                input_channel = out_c
        self.last_channel = _make_divisible(1280 * max(1.0, scale))
        features.append(ConvBNLayer(input_channel, self.last_channel, 1,
                                    act=ReLU6))
        self.features = Sequential(*features)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2),
                                         Linear(self.last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.classifier(x)
        return x


class SqueezeExcite(Layer):
    def __init__(self, channels, reduction=4):
        super().__init__()
        mid = _make_divisible(channels // reduction)
        self.pool = AdaptiveAvgPool2D((1, 1))
        self.fc1 = Conv2D(channels, mid, 1)
        self.relu = ReLU()
        self.fc2 = Conv2D(mid, channels, 1)
        self.hsig = Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _V3Block(Layer):
    def __init__(self, inp, hidden, out, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == out
        layers = []
        if hidden != inp:
            layers.append(ConvBNLayer(inp, hidden, 1, act=act))
        layers.append(ConvBNLayer(hidden, hidden, kernel, stride=stride,
                                  groups=hidden, act=act))
        if use_se:
            layers.append(SqueezeExcite(hidden))
        layers.append(ConvBNLayer(hidden, out, 1, act=None))
        self.block = Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_LARGE = [  # kernel, hidden, out, se, act, stride
    (3, 16, 16, False, ReLU, 1), (3, 64, 24, False, ReLU, 2),
    (3, 72, 24, False, ReLU, 1), (5, 72, 40, True, ReLU, 2),
    (5, 120, 40, True, ReLU, 1), (5, 120, 40, True, ReLU, 1),
    (3, 240, 80, False, Hardswish, 2), (3, 200, 80, False, Hardswish, 1),
    (3, 184, 80, False, Hardswish, 1), (3, 184, 80, False, Hardswish, 1),
    (3, 480, 112, True, Hardswish, 1), (3, 672, 112, True, Hardswish, 1),
    (5, 672, 160, True, Hardswish, 2), (5, 960, 160, True, Hardswish, 1),
    (5, 960, 160, True, Hardswish, 1),
]

_V3_SMALL = [
    (3, 16, 16, True, ReLU, 2), (3, 72, 24, False, ReLU, 2),
    (3, 88, 24, False, ReLU, 1), (5, 96, 40, True, Hardswish, 2),
    (5, 240, 40, True, Hardswish, 1), (5, 240, 40, True, Hardswish, 1),
    (5, 120, 48, True, Hardswish, 1), (5, 144, 48, True, Hardswish, 1),
    (5, 288, 96, True, Hardswish, 2), (5, 576, 96, True, Hardswish, 1),
    (5, 576, 96, True, Hardswish, 1),
]


class MobileNetV3(Layer):
    def __init__(self, cfg, last_channels, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        layers = [ConvBNLayer(3, in_c, 3, stride=2, act=Hardswish)]
        for k, hidden, out, se, act, s in cfg:
            h = _make_divisible(hidden * scale)
            o = _make_divisible(out * scale)
            layers.append(_V3Block(in_c, h, o, k, s, se, act))
            in_c = o
        last_conv = _make_divisible(cfg[-1][1] * scale)
        layers.append(ConvBNLayer(in_c, last_conv, 1, act=Hardswish))
        self.features = Sequential(*layers)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(last_conv, last_channels), Hardswish(), Dropout(0.2),
                Linear(last_channels, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.classifier(x)
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights require network access")
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights require network access")
    return MobileNetV2(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights require network access")
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights require network access")
    return MobileNetV3Small(scale=scale, **kwargs)
