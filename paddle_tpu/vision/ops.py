"""paddle.vision.ops (reference: python/paddle/vision/ops.py — yolo_loss,
yolo_box, deform_conv2d/DeformConv2D, read_file, decode_jpeg). Facade
over the framework's detection/conv/image implementations, keeping the
reference's argument names."""
from __future__ import annotations

from ..ops.detection import yolo_box, yolov3_loss as _yolov3_loss
from ..nn.functional.conv import deformable_conv as _deform
from ..nn.layer_base import Layer
from .image import read_file, decode_jpeg  # noqa: F401

__all__ = ["yolo_loss", "yolo_box", "deform_conv2d", "DeformConv2D",
           "read_file", "decode_jpeg"]


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """reference: vision/ops.py:31 — alias of the fluid yolov3_loss op."""
    return _yolov3_loss(x, gt_box, gt_label, anchors=anchors,
                        anchor_mask=anchor_mask, class_num=class_num,
                        ignore_thresh=ignore_thresh,
                        downsample_ratio=downsample_ratio,
                        gt_score=gt_score,
                        use_label_smooth=use_label_smooth,
                        scale_x_y=scale_x_y)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """reference: vision/ops.py:397 (v2 argument order; mask=None is
    DCNv1, mask given is DCNv2)."""
    return _deform(x, offset, weight, mask=mask, bias=bias, stride=stride,
                   padding=padding, dilation=dilation,
                   deformable_groups=deformable_groups, groups=groups)


class DeformConv2D(Layer):
    """reference: vision/ops.py:601 — layer wrapper over deform_conv2d."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size if isinstance(kernel_size, (list, tuple))
              else (kernel_size, kernel_size))
        self._attrs = dict(stride=stride, padding=padding,
                           dilation=dilation,
                           deformable_groups=deformable_groups,
                           groups=groups)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]],
            weight_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([out_channels], bias_attr,
                                           is_bias=True))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, bias=self.bias,
                             mask=mask, **self._attrs)
