"""Transform classes (reference: python/paddle/vision/transforms/transforms.py
— BaseTransform protocol with _apply_image/_get_params, Compose chaining)."""
from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

from . import functional as F
from .functional import (  # noqa: F401
    to_tensor, normalize, resize, crop, center_crop, hflip, vflip, pad,
    rotate, adjust_brightness, adjust_contrast, adjust_saturation, adjust_hue,
    to_grayscale,
)
from .det_transforms import (  # noqa: F401
    DetCompose, ResizeImage, RandomFlipImage, NormalizeBox, BoxXYXY2XYWH,
    PadBox, NormalizeImage, Permute,
)


class Compose:
    """reference: transforms.py Compose."""

    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data

    def __repr__(self):
        inner = ", ".join(repr(t) for t in self.transforms)
        return f"Compose([{inner}])"


class BaseTransform:
    """reference: transforms.py BaseTransform (keys/_apply_image protocol,
    collapsed to the image-only case the v2.0 zoo uses)."""

    def __init__(self, keys=None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, img):
        return self._apply_image(img)

    def __repr__(self):
        return type(self).__name__ + "()"


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomResizedCrop(BaseTransform):
    """reference: transforms.py RandomResizedCrop (scale/ratio sampling)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = F._to_numpy(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            aspect = np.exp(random.uniform(np.log(self.ratio[0]),
                                           np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                patch = F.crop(arr, top, left, ch, cw)
                return F.resize(patch, self.size, self.interpolation)
        return F.resize(F.center_crop(arr, min(h, w)), self.size,
                        self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = F._to_numpy(img)
        if self.padding is not None:
            arr = F.pad(arr, self.padding, self.fill, self.padding_mode)
        th, tw = self.size
        h, w = arr.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            # padding tuple is (left, top, right, bottom)
            arr = F.pad(arr, (max(tw - w, 0), max(th - h, 0),
                              max(tw - w, 0), max(th - h, 0)),
                        self.fill, self.padding_mode)
            h, w = arr.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(arr, top, left, th, tw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else F._to_numpy(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else F._to_numpy(img)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Transpose(BaseTransform):
    """reference: transforms.py Transpose (HWC->CHW by default)."""

    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = F._to_numpy(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return F._to_numpy(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return F._to_numpy(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return F._to_numpy(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, factor)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return F._to_numpy(img)
        factor = random.uniform(-self.value, self.value)
        return F.adjust_hue(img, factor)


class ColorJitter(BaseTransform):
    """reference: transforms.py ColorJitter — random-order brightness/
    contrast/saturation/hue."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.transforms[i](img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class RandomErasing(BaseTransform):
    """reference: transforms.py RandomErasing."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        arr = np.array(F._to_numpy(img))
        if random.random() >= self.prob:
            return arr
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            aspect = np.exp(random.uniform(np.log(self.ratio[0]),
                                           np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target / aspect)))
            ew = int(round(np.sqrt(target * aspect)))
            if eh < h and ew < w:
                top = random.randint(0, h - eh)
                left = random.randint(0, w - ew)
                arr[top:top + eh, left:left + ew] = self.value
                return arr
        return arr


# reference exports `paddle.vision.transforms.transforms` (submodule)
import sys as _sys
transforms = _sys.modules[__name__]
