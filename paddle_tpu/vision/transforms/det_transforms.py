"""Detection transforms: the ingest pipeline for the YOLOv3 workload.

Reference capability: PaddleDetection's ppdet/data/transform/operators.py
(DecodeImage, ResizeImage, RandomFlipImage, NormalizeBox, PadBox,
NormalizeImage, Permute) feeding the base repo's
fluid/operators/detection/yolov3_loss_op.cc. TPU-first differences:

- samples are plain tuples ``(img, gt_box, gt_label, difficult)`` — no
  mutable sample dict; every operator is a pure function over the tuple;
- output shapes are STATIC: ``PadBox`` fixes the box count and
  ``ResizeImage`` fixes the spatial size, so one (bucket) shape compiles
  one XLA program. Multi-scale training = a small set of size buckets,
  not per-step random shapes (see vision/models/yolov3.py).
"""
from __future__ import annotations

import numpy as np

__all__ = ["DetCompose", "ResizeImage", "RandomFlipImage", "NormalizeBox",
           "BoxXYXY2XYWH", "PadBox", "NormalizeImage", "Permute"]


class DetCompose:
    """Compose over (img, gt_box, gt_label, difficult) tuples."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, sample):
        for t in self.transforms:
            sample = t(sample)
        return sample


class ResizeImage:
    """Resize image to ``(target, target)`` and scale pixel boxes with it
    (ppdet ResizeImage with interp=bilinear, no keep-ratio — YOLO uses
    square inputs)."""

    def __init__(self, target_size=608):
        self.target = int(target_size)

    def __call__(self, sample):
        img, boxes, labels, difficult = sample
        h, w = img.shape[:2]
        t = self.target
        # bilinear via PIL when available; nearest-neighbour numpy fallback
        try:
            from PIL import Image
            im = Image.fromarray(np.asarray(img).astype(np.uint8))
            img2 = np.asarray(im.resize((t, t), Image.BILINEAR))
        except Exception:
            yi = (np.arange(t) * (h / t)).astype(np.int64).clip(0, h - 1)
            xi = (np.arange(t) * (w / t)).astype(np.int64).clip(0, w - 1)
            img2 = np.asarray(img)[yi][:, xi]
        if boxes.size:
            boxes = boxes * np.array([t / w, t / h, t / w, t / h],
                                     np.float32)
        return img2, boxes, labels, difficult


class RandomFlipImage:
    """Horizontal flip with box mirror (ppdet RandomFlipImage)."""

    def __init__(self, prob=0.5, rng=None):
        self.prob = prob
        self.rng = rng or np.random.RandomState(0)

    def __call__(self, sample):
        img, boxes, labels, difficult = sample
        if self.rng.rand() < self.prob:
            w = img.shape[1]
            img = np.ascontiguousarray(img[:, ::-1])
            if boxes.size:
                x1 = boxes[:, 0].copy()
                boxes = boxes.copy()
                boxes[:, 0] = w - boxes[:, 2]
                boxes[:, 2] = w - x1
        return img, boxes, labels, difficult


class NormalizeBox:
    """Pixel xyxy -> [0,1] xyxy (ppdet NormalizeBox)."""

    def __call__(self, sample):
        img, boxes, labels, difficult = sample
        h, w = img.shape[:2]
        if boxes.size:
            boxes = boxes / np.array([w, h, w, h], np.float32)
        return img, boxes, labels, difficult


class BoxXYXY2XYWH:
    """xyxy -> center xywh (ppdet BboxXYXY2XYWH) — the yolov3_loss gt
    format."""

    def __call__(self, sample):
        img, boxes, labels, difficult = sample
        if boxes.size:
            wh = boxes[:, 2:4] - boxes[:, 0:2]
            ctr = boxes[:, 0:2] + wh / 2
            boxes = np.concatenate([ctr, wh], axis=1)
        return img, boxes, labels, difficult


class PadBox:
    """Zero-pad/truncate boxes to a fixed count (ppdet PadBox) — the
    static-shape contract yolov3_loss relies on (w==h==0 marks an empty
    slot)."""

    def __init__(self, num_max_boxes=50):
        self.num = int(num_max_boxes)

    def __call__(self, sample):
        img, boxes, labels, difficult = sample
        m = min(len(boxes), self.num)
        out_b = np.zeros((self.num, 4), np.float32)
        out_l = np.zeros((self.num,), np.int64)
        out_d = np.zeros((self.num,), np.int64)
        if m:
            out_b[:m] = boxes[:m]
            out_l[:m] = labels[:m]
            out_d[:m] = difficult[:m]
        return img, out_b, out_l, out_d


class NormalizeImage:
    """uint8 HWC -> float32, /255, mean/std (ppdet NormalizeImage)."""

    def __init__(self, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225),
                 is_scale=True):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.is_scale = is_scale

    def __call__(self, sample):
        img, boxes, labels, difficult = sample
        img = np.asarray(img, np.float32)
        if self.is_scale:
            img = img / 255.0
        img = (img - self.mean) / self.std
        return img, boxes, labels, difficult


class Permute:
    """HWC -> CHW (ppdet Permute)."""

    def __call__(self, sample):
        img, boxes, labels, difficult = sample
        return (np.ascontiguousarray(np.transpose(img, (2, 0, 1))),
                boxes, labels, difficult)
