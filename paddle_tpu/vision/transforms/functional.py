"""Functional image transforms on numpy HWC arrays (PIL accepted when
installed). reference: python/paddle/vision/transforms/functional.py (+ the
cv2/pil backend split there — here the single backend is numpy/jax, which
keeps the data pipeline dependency-free and feeds device transfer directly).
"""
from __future__ import annotations

import numbers
from typing import Sequence, Tuple

import numpy as np


def _to_numpy(img):
    if isinstance(img, np.ndarray):
        return img
    # PIL.Image duck-type
    if hasattr(img, "convert") and hasattr(img, "size"):
        return np.asarray(img)
    from ...core.tensor import Tensor
    if isinstance(img, Tensor):
        return img.numpy()
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    """uint8 HWC [0,255] -> float32 CHW [0,1] (reference: functional.py
    to_tensor — uint8 input is always rescaled, float input passes through)."""
    arr = _to_numpy(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    was_uint8 = arr.dtype == np.uint8
    arr = arr.astype(np.float32)
    if was_uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    from ...core.tensor import Tensor
    return Tensor(np.ascontiguousarray(arr))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _to_numpy(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    return (arr - mean.reshape(shape)) / std.reshape(shape)


def _interp_resize(arr, h, w):
    """Bilinear resize via jax.image (maps to XLA gather/dot — fast enough
    for host-side preprocessing, exact for tests)."""
    import jax
    import jax.numpy as jnp
    src = jnp.asarray(arr.astype(np.float32))
    out = jax.image.resize(src, (h, w) + arr.shape[2:], method="bilinear")
    res = np.asarray(out)
    if arr.dtype == np.uint8:
        res = np.clip(np.round(res), 0, 255).astype(np.uint8)
    return res


def resize(img, size, interpolation="bilinear"):
    arr = _to_numpy(img)
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if h <= w:
            nh, nw = size, int(size * w / h)
        else:
            nh, nw = int(size * h / w), size
    else:
        nh, nw = size
    return _interp_resize(arr, nh, nw)


def crop(img, top, left, height, width):
    arr = _to_numpy(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = _to_numpy(img)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(arr, top, left, th, tw)


def hflip(img):
    return _to_numpy(img)[:, ::-1]


def vflip(img):
    return _to_numpy(img)[::-1]


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_numpy(img)
    if isinstance(padding, int):
        padding = (padding, padding, padding, padding)  # l, t, r, b
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    l, t, r, b = padding
    pads = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, pads, mode=mode, **kw)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotation by inverse-mapping with nearest sampling; ``expand=True``
    grows the canvas to hold the whole rotated image (reference:
    functional.py rotate)."""
    arr = _to_numpy(img)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else \
        (center[1], center[0])
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    if expand:
        # output canvas = bounding box of the rotated input rectangle
        oh = int(np.ceil(abs(h * cos) + abs(w * sin) - 1e-9))
        ow = int(np.ceil(abs(w * cos) + abs(h * sin) - 1e-9))
        ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
    else:
        oh, ow, ocy, ocx = h, w, cy, cx
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    ys = (yy - ocy) * cos - (xx - ocx) * sin + cy
    xs = (yy - ocy) * sin + (xx - ocx) * cos + cx
    yi = np.clip(np.round(ys).astype(int), 0, h - 1)
    xi = np.clip(np.round(xs).astype(int), 0, w - 1)
    out = arr[yi, xi]
    invalid = (ys < 0) | (ys > h - 1) | (xs < 0) | (xs > w - 1)
    out[invalid] = fill
    return out


def adjust_brightness(img, factor):
    arr = _to_numpy(img).astype(np.float32) * factor
    return _clip_like(arr, img)


def adjust_contrast(img, factor):
    arr = _to_numpy(img).astype(np.float32)
    mean = arr.mean()
    return _clip_like(mean + factor * (arr - mean), img)


def adjust_saturation(img, factor):
    arr = _to_numpy(img).astype(np.float32)
    gray = arr.mean(axis=-1, keepdims=True)
    return _clip_like(gray + factor * (arr - gray), img)


def adjust_hue(img, factor):
    """factor in [-0.5, 0.5]: rotate hue channel in HSV space."""
    arr = _to_numpy(img)
    scale = 255.0 if arr.dtype == np.uint8 else 1.0
    x = arr.astype(np.float32) / scale
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx, mn = x.max(-1), x.min(-1)
    diff = mx - mn + 1e-12
    h = np.zeros_like(mx)
    m = mx == r
    h[m] = ((g - b)[m] / diff[m]) % 6
    m = mx == g
    h[m] = (b - r)[m] / diff[m] + 2
    m = mx == b
    h[m] = (r - g)[m] / diff[m] + 4
    h = (h / 6.0 + factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0)
    v = mx
    i = np.floor(h * 6)
    f = h * 6 - i
    p, q, t = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
    i = i.astype(int) % 6
    out = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q])], axis=-1)
    return _clip_like(out * scale, img)


def to_grayscale(img, num_output_channels=1):
    arr = _to_numpy(img).astype(np.float32)
    gray = (0.299 * arr[..., 0] + 0.587 * arr[..., 1] + 0.114 * arr[..., 2])
    gray = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return _clip_like(gray, img)


def _clip_like(arr, ref):
    ref_arr = _to_numpy(ref)
    if ref_arr.dtype == np.uint8:
        return np.clip(np.round(arr), 0, 255).astype(np.uint8)
    return arr.astype(ref_arr.dtype)
