"""paddle.incubate.optimizer (reference: incubate/optimizer/lookahead.py
LookAhead :26, modelaverage.py ModelAverage :27).

Both wrap an inner optimizer as plain python around its step() — no op
machinery needed; the slow/averaged copies live as jnp buffers keyed by
the parameter uid (same registry shape as optimizer state)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, stable_uid

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """slow <- slow + alpha * (fast - slow) every k steps; fast <- slow
    (arXiv:1907.08610; reference lookahead.py:26)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be in [0, 1], got {alpha}")
        if k < 1:
            raise ValueError(f"k must be a positive integer, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        self._slow = {}

    def _params(self):
        return [p for p in self.inner_optimizer._parameter_list
                if getattr(p, "trainable", True)]

    def step(self):
        # slow params start from the PRE-update values (reference: the
        # slow accumulator initialises from the param at creation)
        for p in self._params():
            uid = stable_uid(p)
            if uid not in self._slow:
                # COPY: the inner optimizer's fused step donates p._data
                # buffers — an aliased stash would be deleted under us
                self._slow[uid] = jnp.array(p._data, copy=True)
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for p in self._params():
                uid = stable_uid(p)
                slow = (self._slow[uid]
                        + self.alpha * (p._data - self._slow[uid]))
                self._slow[uid] = slow
                p._data = jnp.array(slow, copy=True)   # donation-safe
                p._inplace_version += 1

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage:
    """Running (windowed) average of parameters, swapped in for eval via
    apply()/restore() (reference modelaverage.py:27 — there
    sum_1/sum_2/sum_3 accumulator juggling over min/max_average_window;
    here one running sum + count with the same window semantics)."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.avg_window_rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)
        self._params = list(parameters or [])
        self._new = {}      # recent window: uid -> (sum, n)
        self._old = {}      # rotated history: uid -> (sum, n)
        self._updates = 0
        self._backup = None

    def step(self):
        """Accumulate the current parameter values (call after the inner
        optimizer's step). Window rotation per the reference
        (modelaverage.py docstring): when num_accumulates >=
        min_average_window AND >= min(max_average_window,
        num_updates * rate), the recent sum rotates into the history
        tier; history older than max_average_window is dropped."""
        self._updates += 1
        for p in self._params:
            uid = stable_uid(p)
            s, n = self._new.get(uid, (jnp.zeros_like(p._data), 0))
            s, n = s + p._data, n + 1
            thresh = min(self.max_window,
                         max(1, int(self._updates * self.avg_window_rate)))
            if n >= self.min_window and n >= thresh:
                so, no = self._old.get(uid, (0.0, 0))
                if no >= self.max_window:
                    so, no = 0.0, 0            # drop stale history
                self._old[uid] = (so + s, no + n)
                s, n = jnp.zeros_like(p._data), 0
            self._new[uid] = (s, n)

    def apply(self, executor=None, need_restore=True):
        """Swap averaged values into the parameters (context-manager
        style use also works: ``with ma.apply(): evaluate()``)."""
        self._backup = {stable_uid(p): jnp.array(p._data, copy=True)
                        for p in self._params}
        for p in self._params:
            uid = stable_uid(p)
            s, n = self._new.get(uid, (0.0, 0))
            so, no = self._old.get(uid, (0.0, 0))
            if n + no > 0:
                p._data = (s + so) / (n + no)
                p._inplace_version += 1
        ma = self

        class _Ctx:
            def __enter__(self):
                return ma

            def __exit__(self, *a):
                if need_restore:
                    ma.restore()
                return False
        return _Ctx()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            uid = stable_uid(p)
            if uid in self._backup:
                p._data = self._backup[uid]
                p._inplace_version += 1
        self._backup = None
