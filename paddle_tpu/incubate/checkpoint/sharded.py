"""Sharded checkpoint format: per-host shard archives + JSON metadata.

Layout of a checkpoint directory::

    ckpt/
      metadata_<proc>.json   # per-host: format 2 doc (see below)
      shards_<proc>.npz      # per-host: "<key>|<i>" -> shard ndarray
      scalars.json           # non-array leaves (ints, floats, strings)

``metadata_<proc>.json`` (format 2) is
``{"format": 2, "checksums": {"shards_<proc>.npz": "<sha256>"},
"entries": {key: {shape, dtype, spec, shards}}}``; format-1 checkpoints
(a bare ``{key: entry}`` map, no checksums) still load. Format 3 (written
by the atomic commit in ``async_ckpt``) adds a ``"health"`` doc so the
health stamp publishes in the same ``os.replace`` as the shards. The checksum is
verified on load — a flipped bit or truncated shard archive raises
:class:`CheckpointIntegrityError` instead of silently restoring garbage,
and ``TrainEpochRange._restore`` uses that signal to fall back to the
newest intact committed epoch (docs/fault_tolerance.md).

Multi-host jobs write only addressable shards (parallel, no cross-host
traffic); load expects all hosts' files on a shared filesystem (the
reference makes the same assumption for its HDFS checkpoints,
fleet/utils/fs.py).
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...observability import tracer as _otrace
from ...utils.resilience import fault_injector


#: Suffix of the staging directory the atomic commit protocol
#: (``incubate.checkpoint.async_ckpt``) writes into before its single
#: ``os.replace`` publish. Every reader here — ``_is_checkpoint_dir``,
#: ``newest_healthy_checkpoint``, the epoch/snapshot GC walks — must treat
#: ``*.tmp`` paths as invisible: they are by definition uncommitted.
STAGING_SUFFIX = ".tmp"

#: Suffix of the directory the publish step *parks the previous committed
#: checkpoint under* while swapping in a new one for the same path
#: (``os.replace(final, final + ".old")`` → ``os.replace(staging, final)``
#: → rmtree the parked dir). Readers must skip ``*.old`` dirs: during the
#: swap they coexist with (or briefly replace) the final path, and a
#: crash inside the swap window is recovered at startup by
#: ``cleanup_stale_staging`` renaming the parked dir back into place —
#: so a re-save over an existing checkpoint can never leave zero
#: restorable checkpoints.
OLD_SUFFIX = ".old"


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint directory is torn: checksum mismatch or a shard archive
    referenced by metadata is missing."""


def _sha256_of(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _flatten(tree, prefix=""):
    """Nested dict/list -> {joined_key: leaf}."""
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    else:
        out[prefix[:-1]] = tree
    return out


def _unflatten(flat: Dict[str, Any]):
    root: Dict[str, Any] = {}
    for key, val in flat.items():
        parts = key.split("/")
        d = root
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = val
    return root


def _spec_of(raw):
    """PartitionSpec axis names of a jax.Array, or None."""
    sh = getattr(raw, "sharding", None)
    if sh is None or not hasattr(sh, "spec"):
        return None
    return [list(ax) if isinstance(ax, tuple) else ax for ax in sh.spec]


def _slices_of(shard, ndim):
    idx = shard.index
    out = []
    for d in range(ndim):
        s = idx[d] if d < len(idx) else slice(None)
        out.append([s.start, s.stop] if s.start is not None or s.stop is not None
                   else None)
    return out


def save_sharded(state, path: str, overwrite: bool = True):
    """Write ``state`` (nested dict/list of Tensors/arrays/scalars) as a
    sharded checkpoint directory. Safe to call from every process of a
    multi-host job — each writes its own files."""
    with _otrace.span("checkpoint/save", {"path": path}):
        return _save_sharded_impl(state, path, overwrite)


def _save_sharded_impl(state, path: str, overwrite: bool):
    os.makedirs(path, exist_ok=True)
    proc = jax.process_index()
    flat = _flatten(state)
    meta: Dict[str, Any] = {}
    shard_blobs: Dict[str, np.ndarray] = {}
    scalars: Dict[str, Any] = {}
    for key, val in flat.items():
        raw = val._data if isinstance(val, Tensor) else val
        if isinstance(raw, (int, float, str, bool, type(None))):
            scalars[key] = raw
            continue
        if isinstance(raw, np.ndarray):
            raw = jnp.asarray(raw)
        entry = {"shape": list(raw.shape), "dtype": str(raw.dtype),
                 "spec": _spec_of(raw), "shards": []}
        for i, s in enumerate(getattr(raw, "addressable_shards", [])) or []:
            blob_key = f"{key}|{i}"
            shard_blobs[blob_key] = np.asarray(s.data)
            entry["shards"].append(
                {"blob": blob_key, "index": _slices_of(s, raw.ndim)})
        if not entry["shards"]:  # plain value with no shard view
            blob_key = f"{key}|0"
            shard_blobs[blob_key] = np.asarray(raw)
            entry["shards"].append({"blob": blob_key, "index": None})
        meta[key] = entry
    tmp = os.path.join(path, f".tmp_shards_{proc}.npz")
    np.savez(tmp, **shard_blobs)
    shards_name = f"shards_{proc}.npz"
    os.replace(tmp, os.path.join(path, shards_name))
    # fault site "save": a crash here leaves shard archives without
    # metadata — exactly the torn-checkpoint shape _restore must survive
    fault_injector().fire("save")
    doc = {"format": 2,
           "checksums": {shards_name: _sha256_of(
               os.path.join(path, shards_name))},
           "entries": meta}
    mtmp = os.path.join(path, f".tmp_metadata_{proc}.json")
    with open(mtmp, "w") as f:
        json.dump(doc, f)
    os.replace(mtmp, os.path.join(path, f"metadata_{proc}.json"))
    if proc == 0:
        stmp = os.path.join(path, f".tmp_scalars_{os.getpid()}.json")
        with open(stmp, "w") as f:
            json.dump(scalars, f)
        os.replace(stmp, os.path.join(path, "scalars.json"))


def _corrupt_first_shard_file(path: str):
    """FaultInjector ``load:N:corrupt`` action: flip one byte near the end
    of the first shard archive (deterministic torn-checkpoint simulation)."""
    for fn in sorted(os.listdir(path)):
        if fn.startswith("shards_") and fn.endswith(".npz"):
            full = os.path.join(path, fn)
            with open(full, "r+b") as f:
                f.seek(-1, os.SEEK_END)
                byte = f.read(1)
                f.seek(-1, os.SEEK_END)
                f.write(bytes([byte[0] ^ 0xFF]))
            return


def verify_checkpoint(path: str):
    """Checksum-verify a checkpoint directory without loading it.

    Raises :class:`CheckpointIntegrityError` when a shard archive referenced
    by a format-2 metadata file is missing or fails its sha256; format-1
    metadata (no checksums) only gets the existence check. Also fails when
    the directory has shard archives but no metadata at all (a save that
    died between the two writes), and when any *individual* host's shard
    archive lacks its ``metadata_<proc>.json`` — the multi-host commit
    protocol writes the manifest last per host, so ``shards_3.npz`` without
    ``metadata_3.json`` means host 3 (or the coordinator, mid-commit) died
    inside the window; loading anyway would silently zero-fill every slice
    host 3 owned."""
    if not os.path.isdir(path):
        raise CheckpointIntegrityError(f"{path} is not a directory")
    names = os.listdir(path)
    meta_files = [n for n in names if n.startswith("metadata_")
                  and n.endswith(".json")]
    shard_files = [n for n in names if n.startswith("shards_")
                   and n.endswith(".npz")]
    if not meta_files:
        raise CheckpointIntegrityError(
            f"{path}: no metadata_*.json "
            f"({'shards present — torn save' if shard_files else 'empty'})")
    meta_procs = {n[len("metadata_"):-len(".json")] for n in meta_files}
    orphan_shards = sorted(
        n for n in shard_files
        if n[len("shards_"):-len(".npz")] not in meta_procs)
    if orphan_shards:
        raise CheckpointIntegrityError(
            f"{path}: shard archive(s) without a committing manifest: "
            f"{', '.join(orphan_shards)} (a host died between its shard "
            f"write and its metadata commit — slices owned by that host "
            f"would restore as zeros)")
    for fn in sorted(meta_files):
        try:
            with open(os.path.join(path, fn)) as f:
                m = json.load(f)
        except ValueError as e:
            raise CheckpointIntegrityError(
                f"{path}: {fn} is not parsable JSON ({e})") from e
        proc = fn[len("metadata_"):-len(".json")]
        expect = (m.get("checksums", {}) if m.get("format") in (2, 3)
                  else {f"shards_{proc}.npz": None})
        for shards_name, digest in expect.items():
            full = os.path.join(path, shards_name)
            if not os.path.exists(full):
                raise CheckpointIntegrityError(
                    f"{path}: {fn} references missing {shards_name}")
            if digest is not None and _sha256_of(full) != digest:
                raise CheckpointIntegrityError(
                    f"{path}: checksum mismatch for {shards_name} "
                    f"(expected {digest[:12]}…)")


#: Health-stamp sidecar the numerical-anomaly sentinel writes next to the
#: shard/metadata files. Integrity (checksums) says the bytes are intact;
#: the stamp says the *state* was numerically sane when saved. A checkpoint
#: without a stamp is assumed healthy — every pre-sentinel checkpoint stays
#: restorable.
HEALTH_STAMP_FILE = "health.json"


def write_health_stamp(path: str, healthy: bool, step: Optional[int] = None,
                       reason: Optional[str] = None):
    """Write (or overwrite) the health-stamp sidecar on checkpoint dir
    ``path``. tmp+replace so a crash mid-write leaves the previous stamp,
    never a torn one. The staging name is per-process: on a shared
    checkpoint dir every dp rank sees the same divergence and stamps
    concurrently — identical content, so racing replaces are benign, but a
    shared tmp name is not (the first rename consumes it and the rest
    raise). ``.tmp_`` prefix so debris from a host killed mid-write is
    swept by ``cleanup_stale_staging``."""
    stamp = {"healthy": bool(healthy), "time": time.time()}
    if step is not None:
        stamp["step"] = int(step)
    if reason is not None:
        stamp["reason"] = str(reason)
    final = os.path.join(path, HEALTH_STAMP_FILE)
    tmp = os.path.join(path, f".tmp_health_{os.getpid()}.json")
    with open(tmp, "w") as f:
        json.dump(stamp, f)
    os.replace(tmp, final)


def read_health_stamp(path: str) -> Dict[str, Any]:
    """Read the health stamp of checkpoint dir ``path``.

    Prefers the ``health.json`` sidecar (it is rewritable, so a retroactive
    ``mark_unhealthy`` after commit still wins); when the sidecar is missing
    or unparsable, falls back to the ``health`` doc format-3 metadata
    carries inside the atomic commit (closing the old stamp-after-rename
    window). With neither, reads as ``{"healthy": True}`` — absence of
    evidence of sickness is health (backward compat with stamp-less
    checkpoints)."""
    full = os.path.join(path, HEALTH_STAMP_FILE)
    try:
        with open(full) as f:
            stamp = json.load(f)
    except (OSError, ValueError):
        stamp = _manifest_health(path)
    if not isinstance(stamp, dict):
        return {"healthy": True}
    stamp.setdefault("healthy", True)
    return stamp


def _manifest_health(path: str) -> Dict[str, Any]:
    """Health doc embedded in a format-3 metadata file, else healthy."""
    try:
        names = os.listdir(path)
    except OSError:
        return {"healthy": True}
    for fn in sorted(names):
        if not (fn.startswith("metadata_") and fn.endswith(".json")):
            continue
        try:
            with open(os.path.join(path, fn)) as f:
                m = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(m, dict) and isinstance(m.get("health"), dict):
            return dict(m["health"])
    return {"healthy": True}


def _is_checkpoint_dir(path: str) -> bool:
    # *.tmp is the async-commit staging dir: it holds metadata files but is
    # by definition uncommitted — no restore walk may ever pick it up.
    # *.old is the previous checkpoint parked mid-swap by a re-save over
    # the same path: complete but superseded (and recovered/removed by the
    # startup sweep), so restore walks must not race the swap for it.
    stripped = path.rstrip(os.sep)
    if stripped.endswith(STAGING_SUFFIX) or stripped.endswith(OLD_SUFFIX):
        return False
    try:
        names = os.listdir(path)
    except OSError:
        return False
    return any(n.startswith("metadata_") and n.endswith(".json")
               for n in names)


def swap_eligible(path: str, verify: bool = True):
    """Gate for the serving fleet's live weight hot-swap: may ``path`` be
    rolled onto live replicas? Returns ``(ok, reason)`` — never raises.

    Eligible means the two-phase commit finished (a committed checkpoint
    dir, not ``*.tmp`` staging or a ``*.old`` parked-previous), the health
    stamp vouches for it (the sentinel did not flag divergence), and, with
    ``verify``, the checksum sweep passes. The same three gates the
    resurrection boot path applies, exposed as a predicate so the swap
    controller can refuse a roll BEFORE draining any replica."""
    if not _is_checkpoint_dir(path):
        return False, (
            f"{path} is not a committed checkpoint directory (staging/"
            f"parked dirs and metadata-less paths are never eligible)")
    stamp = read_health_stamp(path)
    if not stamp.get("healthy", True):
        return False, (
            f"{path} is stamped unhealthy"
            + (f" ({stamp['reason']})" if stamp.get("reason") else ""))
    if verify:
        try:
            verify_checkpoint(path)
        except CheckpointIntegrityError as e:
            return False, f"{path} failed checksum verification: {e}"
    return True, "eligible"


def newest_healthy_checkpoint(root: str,
                              verify: bool = True) -> Optional[str]:
    """Walk ``root`` for the newest checkpoint that is health-stamped sane
    (and, with ``verify``, passes the checksum sweep). The boot path of a
    resurrecting serving replica: pick the freshest state the sentinel
    vouched for, skipping newer-but-diverged saves.

    ``root`` may itself be a checkpoint dir, or a directory of checkpoint
    subdirs (``step_100/``, ``step_200/``, …). Candidates are ordered by
    the numeric suffix in their name when one exists (``step_200`` >
    ``step_100``), falling back to mtime. Unhealthy, unverifiable, or
    corrupt candidates are skipped with a warning; returns None when
    nothing survives.
    """
    import re
    import warnings
    if _is_checkpoint_dir(root):
        cands = [root]
    else:
        try:
            names = sorted(os.listdir(root))
        except OSError:
            return None
        cands = [os.path.join(root, n) for n in names
                 if _is_checkpoint_dir(os.path.join(root, n))]

    def _order(p):
        m = re.search(r"(\d+)$", os.path.basename(p.rstrip(os.sep)))
        step = int(m.group(1)) if m else -1
        try:
            mtime = os.path.getmtime(p)
        except OSError:
            mtime = 0.0
        return (step, mtime)

    for cand in sorted(cands, key=_order, reverse=True):
        stamp = read_health_stamp(cand)
        if not stamp.get("healthy", True):
            warnings.warn(
                f"skipping checkpoint {cand}: health stamp says unhealthy"
                f" ({stamp.get('reason', 'no reason recorded')})")
            continue
        if verify:
            try:
                verify_checkpoint(cand)
            except CheckpointIntegrityError as e:
                warnings.warn(f"skipping checkpoint {cand}: {e}")
                continue
        return cand
    return None


def _meta_entries(m):
    """Entries map from a format-2/3 doc or a legacy format-1 bare map."""
    if isinstance(m, dict) and m.get("format") in (2, 3):
        return m["entries"]
    return m


def load_sharded(path: str, mesh=None, return_tensor: bool = True,
                 verify: bool = True):
    """Load a sharded checkpoint, reassembling global arrays from every
    host's shard files and (when ``mesh`` is given) re-sharding each array
    onto the current mesh using its recorded PartitionSpec — axes missing
    from the new mesh degrade to replication (resharding on restore).

    ``verify=True`` (default) checksum-verifies every shard archive first
    and raises :class:`CheckpointIntegrityError` on a torn checkpoint."""
    with _otrace.span("checkpoint/load", {"path": path}):
        return _load_sharded_impl(path, mesh, return_tensor, verify)


def _load_sharded_impl(path: str, mesh, return_tensor: bool, verify: bool):
    from jax.sharding import NamedSharding, PartitionSpec as P

    if fault_injector().fire("load") == "corrupt":
        _corrupt_first_shard_file(path)
    if verify:
        verify_checkpoint(path)

    metas = {}
    for fn in sorted(os.listdir(path)):
        if fn.startswith("metadata_"):
            with open(os.path.join(path, fn)) as f:
                m = json.load(f)
            proc = fn[len("metadata_"):-len(".json")]
            for k, v in _meta_entries(m).items():
                metas.setdefault(k, []).append((proc, v))
    blobs = {}
    for fn in sorted(os.listdir(path)):
        if fn.startswith("shards_") and fn.endswith(".npz"):
            proc = fn[len("shards_"):-len(".npz")]
            with np.load(os.path.join(path, fn)) as z:
                for k in z.files:
                    blobs[(proc, k)] = z[k]

    flat: Dict[str, Any] = {}
    for key, entries in metas.items():
        shape = tuple(entries[0][1]["shape"])
        dtype = entries[0][1]["dtype"]
        spec = entries[0][1]["spec"]
        full = np.zeros(shape, dtype=dtype) if shape else None
        for proc, e in entries:
            for sh in e["shards"]:
                data = blobs[(proc, sh["blob"])]
                if sh["index"] is None or not shape:
                    full = data
                    continue
                sl = tuple(slice(None) if s is None else slice(s[0], s[1])
                           for s in sh["index"])
                full[sl] = data
        arr = _reshard(full, spec, mesh)
        flat[key] = Tensor(arr) if return_tensor else arr

    scalars_path = os.path.join(path, "scalars.json")
    if os.path.exists(scalars_path):
        with open(scalars_path) as f:
            flat.update(json.load(f))
    return _unflatten(flat)


def _reshard(full_np, spec, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    if mesh is None or spec is None:
        return jnp.asarray(full_np)
    axes = []
    names = set(mesh.axis_names)
    for ax in spec:
        if ax is None:
            axes.append(None)
        elif isinstance(ax, list):
            keep = [a for a in ax if a in names]
            axes.append(tuple(keep) if keep else None)
        else:
            axes.append(ax if ax in names else None)
    return jax.device_put(full_np, NamedSharding(mesh, P(*axes)))


class AsyncSaver:
    """Asynchronous checkpointing: the device→host fetch + file write run on
    a background thread so the training loop keeps stepping (orbax-style;
    the reference's PS tables save server-side for the same reason)."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error = None

    def save(self, state, path: str, on_done=None):
        self.wait()
        # snapshot raw references now; numpy conversion happens off-thread
        flat = _flatten(state)
        snapshot = _unflatten({k: (v._data if isinstance(v, Tensor) else v)
                               for k, v in flat.items()})

        def run():
            try:
                save_sharded(snapshot, path)
                if on_done is not None:
                    on_done()
            except Exception as e:  # surfaced on wait()
                self._error = e

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err
