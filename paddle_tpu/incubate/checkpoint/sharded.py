"""Sharded checkpoint format: per-host shard archives + JSON metadata.

Layout of a checkpoint directory::

    ckpt/
      metadata_<proc>.json   # per-host: key -> {shape, dtype, spec, shards}
      shards_<proc>.npz      # per-host: "<key>|<i>" -> shard ndarray
      scalars.json           # non-array leaves (ints, floats, strings)

Multi-host jobs write only addressable shards (parallel, no cross-host
traffic); load expects all hosts' files on a shared filesystem (the
reference makes the same assumption for its HDFS checkpoints,
fleet/utils/fs.py).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor


def _flatten(tree, prefix=""):
    """Nested dict/list -> {joined_key: leaf}."""
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    else:
        out[prefix[:-1]] = tree
    return out


def _unflatten(flat: Dict[str, Any]):
    root: Dict[str, Any] = {}
    for key, val in flat.items():
        parts = key.split("/")
        d = root
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = val
    return root


def _spec_of(raw):
    """PartitionSpec axis names of a jax.Array, or None."""
    sh = getattr(raw, "sharding", None)
    if sh is None or not hasattr(sh, "spec"):
        return None
    return [list(ax) if isinstance(ax, tuple) else ax for ax in sh.spec]


def _slices_of(shard, ndim):
    idx = shard.index
    out = []
    for d in range(ndim):
        s = idx[d] if d < len(idx) else slice(None)
        out.append([s.start, s.stop] if s.start is not None or s.stop is not None
                   else None)
    return out


def save_sharded(state, path: str, overwrite: bool = True):
    """Write ``state`` (nested dict/list of Tensors/arrays/scalars) as a
    sharded checkpoint directory. Safe to call from every process of a
    multi-host job — each writes its own files."""
    os.makedirs(path, exist_ok=True)
    proc = jax.process_index()
    flat = _flatten(state)
    meta: Dict[str, Any] = {}
    shard_blobs: Dict[str, np.ndarray] = {}
    scalars: Dict[str, Any] = {}
    for key, val in flat.items():
        raw = val._data if isinstance(val, Tensor) else val
        if isinstance(raw, (int, float, str, bool, type(None))):
            scalars[key] = raw
            continue
        if isinstance(raw, np.ndarray):
            raw = jnp.asarray(raw)
        entry = {"shape": list(raw.shape), "dtype": str(raw.dtype),
                 "spec": _spec_of(raw), "shards": []}
        for i, s in enumerate(getattr(raw, "addressable_shards", [])) or []:
            blob_key = f"{key}|{i}"
            shard_blobs[blob_key] = np.asarray(s.data)
            entry["shards"].append(
                {"blob": blob_key, "index": _slices_of(s, raw.ndim)})
        if not entry["shards"]:  # plain value with no shard view
            blob_key = f"{key}|0"
            shard_blobs[blob_key] = np.asarray(raw)
            entry["shards"].append({"blob": blob_key, "index": None})
        meta[key] = entry
    tmp = os.path.join(path, f".tmp_shards_{proc}.npz")
    np.savez(tmp, **shard_blobs)
    os.replace(tmp, os.path.join(path, f"shards_{proc}.npz"))
    with open(os.path.join(path, f"metadata_{proc}.json"), "w") as f:
        json.dump(meta, f)
    if proc == 0:
        with open(os.path.join(path, "scalars.json"), "w") as f:
            json.dump(scalars, f)


def load_sharded(path: str, mesh=None, return_tensor: bool = True):
    """Load a sharded checkpoint, reassembling global arrays from every
    host's shard files and (when ``mesh`` is given) re-sharding each array
    onto the current mesh using its recorded PartitionSpec — axes missing
    from the new mesh degrade to replication (resharding on restore)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    metas = {}
    for fn in sorted(os.listdir(path)):
        if fn.startswith("metadata_"):
            with open(os.path.join(path, fn)) as f:
                m = json.load(f)
            proc = fn[len("metadata_"):-len(".json")]
            for k, v in m.items():
                metas.setdefault(k, []).append((proc, v))
    blobs = {}
    for fn in sorted(os.listdir(path)):
        if fn.startswith("shards_") and fn.endswith(".npz"):
            proc = fn[len("shards_"):-len(".npz")]
            with np.load(os.path.join(path, fn)) as z:
                for k in z.files:
                    blobs[(proc, k)] = z[k]

    flat: Dict[str, Any] = {}
    for key, entries in metas.items():
        shape = tuple(entries[0][1]["shape"])
        dtype = entries[0][1]["dtype"]
        spec = entries[0][1]["spec"]
        full = np.zeros(shape, dtype=dtype) if shape else None
        for proc, e in entries:
            for sh in e["shards"]:
                data = blobs[(proc, sh["blob"])]
                if sh["index"] is None or not shape:
                    full = data
                    continue
                sl = tuple(slice(None) if s is None else slice(s[0], s[1])
                           for s in sh["index"])
                full[sl] = data
        arr = _reshard(full, spec, mesh)
        flat[key] = Tensor(arr) if return_tensor else arr

    scalars_path = os.path.join(path, "scalars.json")
    if os.path.exists(scalars_path):
        with open(scalars_path) as f:
            flat.update(json.load(f))
    return _unflatten(flat)


def _reshard(full_np, spec, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    if mesh is None or spec is None:
        return jnp.asarray(full_np)
    axes = []
    names = set(mesh.axis_names)
    for ax in spec:
        if ax is None:
            axes.append(None)
        elif isinstance(ax, list):
            keep = [a for a in ax if a in names]
            axes.append(tuple(keep) if keep else None)
        else:
            axes.append(ax if ax in names else None)
    return jax.device_put(full_np, NamedSharding(mesh, P(*axes)))


class AsyncSaver:
    """Asynchronous checkpointing: the device→host fetch + file write run on
    a background thread so the training loop keeps stepping (orbax-style;
    the reference's PS tables save server-side for the same reason)."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error = None

    def save(self, state, path: str, on_done=None):
        self.wait()
        # snapshot raw references now; numpy conversion happens off-thread
        flat = _flatten(state)
        snapshot = _unflatten({k: (v._data if isinstance(v, Tensor) else v)
                               for k, v in flat.items()})

        def run():
            try:
                save_sharded(snapshot, path)
                if on_done is not None:
                    on_done()
            except Exception as e:  # surfaced on wait()
                self._error = e

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err
