"""Crash-consistent asynchronous checkpointing.

The scaling tax this removes: a synchronous snapshot (sentinel rollback,
epoch auto-checkpoint) blocks the train loop on a device→host fetch plus
file I/O that grows with param count. Following LazyTensor's
async-dispatch discipline (PAPERS.md — keep the accelerator busy while
the host works), :class:`AsyncCheckpointer` moves the whole save off the
step path:

1. **Snapshot off the step path** — ``save()`` takes an *async on-device
   copy* of each array (the fused optimizer step donates param buffers,
   so a bare reference would be deleted under the writer) and starts the
   device→host DMA with ``copy_to_host_async`` — non-blocking
   double-buffering; the blocking materialization happens on the writer
   thread.
2. **Bounded queue + coalescing** — at most ``queue_depth`` snapshots
   wait; when full, the *oldest unwritten* snapshot is superseded by the
   newer one (its ticket reports ``superseded``) instead of ever
   blocking the trainer.
3. **Two-phase atomic commit** (single-process jobs) — shards, the
   sha256 manifest (with the health stamp folded in — no
   stamp-after-rename window) and sidecars land in a ``<path>.tmp``
   staging dir, every file and the dir are fsynced, then one
   ``os.replace`` publishes the checkpoint. Re-saves over an existing
   path first *park* the previous commit as ``<path>.old`` (another
   ``os.replace``), publish, then remove the parked dir — at every
   instant at least one complete checkpoint exists, and a crash inside
   the swap is recovered at startup (``cleanup_stale_staging`` renames
   the parked dir back). Readers (``load_sharded``, newest-healthy
   walks, elastic resume, replica resurrection) can never observe a
   torn checkpoint: it either does not exist yet or is complete.

**Multi-host jobs** (``jax.process_count() > 1``) cannot use the
dir-level swap: on a shared filesystem each host owns only its
``shards_<proc>.npz``/``metadata_<proc>.json`` pair, and any host
renaming or deleting the shared directory would destroy its peers'
files. There the commit degrades to the *cooperative* protocol
``save_sharded`` already uses — per-host file-level tmp+``os.replace``
into the final dir, manifest (with inline health doc) strictly after
its shard archive — so a torn write is *detectable* (checksums +
missing-file checks in ``verify_checkpoint``, run by every restore
walk) rather than invisible. Same "no torn read ever surfaces"
contract, host-local decisions only: the async writer thread never
crosses a host barrier (coalescing is timing-dependent per host, so a
barrier could pair different snapshots across hosts and deadlock);
the synchronous ``commit_checkpoint``, which *is* called collectively,
does barrier so its return means the checkpoint is complete.

I/O failures retry on the writer thread with the existing backoff
substrate (:func:`~paddle_tpu.utils.resilience.retry_call`) and then
**degrade to skip-with-counter** (``ckpt.async.degraded_skips``) instead
of killing the step loop — a full disk makes you lose a snapshot, not
the job.

Fault sites (chaos campaign, docs/fault_tolerance.md): ``ckpt_fetch``,
``ckpt_shard_write``, ``ckpt_pre_rename``, ``ckpt_swap_window`` (previous
checkpoint parked, new one not yet published), ``ckpt_post_rename`` fire
at the matching pipeline stage; actions ``kill_during_commit`` (hard exit),
``torn_write`` (truncate the staged archive after checksumming),
``disk_full`` (raise ENOSPC), ``slow_io`` (stall the writer) are
interpreted here.

PTA002 polices this file as a hot path: the *step-path* entry points
(``save``/enqueue) must stay free of blocking I/O and device fetches;
writer-thread internals carry ``noqa`` justifications.
"""
from __future__ import annotations

import errno
import json
import os
import shutil
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Set

import numpy as np

from ...core import monitor as _monitor
from ...observability import flight as _flight
from ...observability import tracer as _otrace
from ...utils.resilience import RetryError, fault_injector, retry_call
from .sharded import (HEALTH_STAMP_FILE, OLD_SUFFIX, STAGING_SUFFIX,
                      _flatten, _sha256_of, _slices_of, _spec_of)

#: injected ``slow_io`` stall per fire (seconds); env-tunable so chaos
#: tests can widen the commit window enough to land a real SIGKILL in it.
SLOW_IO_SECONDS = float(os.environ.get("PADDLE_TPU_FAULT_SLOW_IO_S", "0.25"))


class CommitError(RuntimeError):
    """A checkpoint commit failed after exhausting its I/O retries."""


def _fire(site: str, shard_path: Optional[str] = None):
    """Count one FaultInjector occurrence of ``site`` and interpret the
    checkpoint-flavored actions. ``crash``/``kill_during_commit`` (hard
    exit) and ``raise`` are executed inside ``fire`` itself."""
    action = fault_injector().fire(site)
    if action is None:
        return
    if action == "disk_full":
        raise OSError(errno.ENOSPC,
                      f"injected disk_full at {site}")
    if action == "slow_io":
        time.sleep(SLOW_IO_SECONDS)  # noqa: PTA002 -- injected writer-thread stall; never reachable from save()
    elif action == "torn_write" and shard_path is not None:
        # simulate a write torn by power loss AFTER the checksum was
        # recorded: the manifest claims the full digest, verification
        # must catch the mismatch on load
        size = os.path.getsize(shard_path)
        with open(shard_path, "r+b") as f:  # noqa: PTA002 -- fault-injection corruption, writer thread only
            f.truncate(max(1, size // 2))


def _fsync_file(path: str):
    fd = os.open(path, os.O_RDONLY)  # noqa: PTA002 -- durability fsync, writer thread only
    try:
        os.fsync(fd)  # noqa: PTA002 -- durability fsync, writer thread only
    finally:
        os.close(fd)


def _fsync_dir(path: str):
    """fsync a directory so the rename/creat entries are durable; some
    filesystems refuse dir fsync — that costs durability, not atomicity."""
    try:
        fd = os.open(path, os.O_RDONLY)  # noqa: PTA002 -- durability fsync, writer thread only
    except OSError:
        return
    try:
        os.fsync(fd)  # noqa: PTA002 -- durability fsync, writer thread only
    except OSError:
        pass
    finally:
        os.close(fd)


def _materialize(flat: Dict[str, Any]):
    """Device→host fetch: flat {key: raw} → (meta entries, shard blobs,
    scalars). Mirrors ``sharded._save_sharded_impl``'s shard walk; runs
    ONLY on the writer thread (or inside a sync ``commit_checkpoint``) —
    never on the step path."""
    import jax.numpy as jnp
    meta: Dict[str, Any] = {}
    blobs: Dict[str, np.ndarray] = {}
    scalars: Dict[str, Any] = {}
    for key, raw in flat.items():
        if isinstance(raw, (int, float, str, bool, type(None))):
            scalars[key] = raw
            continue
        if isinstance(raw, np.ndarray):
            raw = jnp.asarray(raw)
        entry = {"shape": list(raw.shape), "dtype": str(raw.dtype),
                 "spec": _spec_of(raw), "shards": []}
        for i, s in enumerate(getattr(raw, "addressable_shards", [])) or []:
            blob_key = f"{key}|{i}"
            blobs[blob_key] = np.asarray(s.data)  # noqa: PTA002 -- the writer-thread device->host fetch; sanctioned off the step path
            entry["shards"].append(
                {"blob": blob_key, "index": _slices_of(s, raw.ndim)})
        if not entry["shards"]:
            blob_key = f"{key}|0"
            blobs[blob_key] = np.asarray(raw)  # noqa: PTA002 -- the writer-thread device->host fetch; sanctioned off the step path
            entry["shards"].append({"blob": blob_key, "index": None})
        meta[key] = entry
    return meta, blobs, scalars


def _health_doc(healthy: bool, step: Optional[int],
                reason: Optional[str]) -> Dict[str, Any]:
    doc: Dict[str, Any] = {"healthy": bool(healthy), "time": time.time()}
    if step is not None:
        doc["step"] = int(step)
    if reason is not None:
        doc["reason"] = str(reason)
    return doc


def _write_staged(staging: str, meta, blobs, scalars, health,
                  fsync: bool = True):
    """Phase 1: write every checkpoint file into ``staging``. Layout is
    byte-compatible with ``sharded.save_sharded`` (plus the manifest's
    inline health doc), so every existing reader works unchanged."""
    import jax
    if os.path.isdir(staging):  # debris from a writer that died mid-stage
        shutil.rmtree(staging, ignore_errors=True)  # noqa: PTA002 -- staging cleanup, writer thread only
    os.makedirs(staging, exist_ok=True)  # noqa: PTA002 -- staging setup, writer thread only
    proc = jax.process_index()
    shards_name = f"shards_{proc}.npz"
    shards_path = os.path.join(staging, shards_name)
    with open(shards_path, "wb") as f:  # noqa: PTA002 -- shard archive write, writer thread only
        np.savez(f, **blobs)  # noqa: PTA002 -- shard archive write, writer thread only
    if fsync:
        _fsync_file(shards_path)
    digest = _sha256_of(shards_path)
    # fire AFTER the checksum: torn_write must leave a manifest that
    # claims the full digest so verify-on-load catches the tear
    _fire("ckpt_shard_write", shards_path)
    doc = {"format": 3,
           "checksums": {shards_name: digest},
           "health": dict(health),
           "entries": meta}
    sidecars = [(f"metadata_{proc}.json", doc)]
    if proc == 0:
        # shared (not per-host) files: one writer, matching save_sharded
        sidecars += [(HEALTH_STAMP_FILE, dict(health)),
                     ("scalars.json", scalars)]
    for name, payload in sidecars:
        p = os.path.join(staging, name)
        with open(p, "w") as f:  # noqa: PTA002 -- manifest/sidecar write, writer thread only
            json.dump(payload, f)
        if fsync:
            _fsync_file(p)
    if fsync:
        _fsync_dir(staging)


def _publish(staging: str, final: str):
    """Phase 2: the atomic publish. A crash strictly before the final
    ``os.replace`` leaves only ``*.tmp``/``*.old`` dirs every reader
    skips (and the startup sweep recovers); a crash strictly after
    leaves a complete committed checkpoint.

    Re-saves over an existing path must never enter a state with zero
    restorable checkpoints (FaultToleranceCallback re-saves "latest" in
    place, so there may be no older sibling to fall back to): the
    previous commit is *parked* atomically as ``final + ".old"`` —
    ``os.replace`` cannot swap non-empty dirs in one shot — then the new
    dir is renamed in, then the parked dir is removed. A crash inside
    that window leaves the parked dir, which ``cleanup_stale_staging``
    renames back into place on restart."""
    _fire("ckpt_pre_rename")
    old = final + OLD_SUFFIX
    if os.path.isdir(old):
        # debris from a previous crashed swap whose final was republished
        shutil.rmtree(old)  # noqa: PTA002 -- stale parked-dir removal, writer thread only
    if os.path.isdir(final):
        os.replace(final, old)  # noqa: PTA002 -- atomic old-checkpoint parking, writer thread only
        _fire("ckpt_swap_window")
    os.replace(staging, final)  # noqa: PTA002 -- the atomic publish, writer thread only
    _fsync_dir(os.path.dirname(os.path.abspath(final)))
    shutil.rmtree(old, ignore_errors=True)  # noqa: PTA002 -- parked-dir removal post-publish, writer thread only
    _fire("ckpt_post_rename")


def _write_cooperative(final: str, meta, blobs, scalars, health,
                       fsync: bool = True):
    """Multi-host commit: per-host *file-level* atomicity into the shared
    ``final`` dir, never touching peers' files.

    Directory-level swap atomicity is impossible here without cross-host
    coordination — any host rmtree'ing or renaming the shared dir would
    destroy its peers' in-progress or already-published shards (each
    host owns only ``shards_<proc>.npz`` + ``metadata_<proc>.json``).
    So this keeps ``save_sharded``'s cooperative protocol: shard archive
    first, manifest (checksums + inline health doc) strictly after via
    tmp+``os.replace``, shared sidecars from process 0 only. A crash
    leaves either no manifest (``verify_checkpoint``: torn save) or a
    manifest whose checksums expose any half-written archive — readers
    verify before trusting, so a torn state is detected and skipped
    rather than invisible."""
    import jax
    proc = jax.process_index()
    os.makedirs(final, exist_ok=True)  # noqa: PTA002 -- cooperative commit, writer thread only
    shards_name = f"shards_{proc}.npz"
    shards_path = os.path.join(final, shards_name)
    tmp = os.path.join(final, f".tmp_{shards_name}")
    with open(tmp, "wb") as f:  # noqa: PTA002 -- shard archive write, writer thread only
        np.savez(f, **blobs)  # noqa: PTA002 -- shard archive write, writer thread only
    if fsync:
        _fsync_file(tmp)
    os.replace(tmp, shards_path)  # noqa: PTA002 -- per-file atomic publish, writer thread only
    digest = _sha256_of(shards_path)
    _fire("ckpt_shard_write", shards_path)
    doc = {"format": 3,
           "checksums": {shards_name: digest},
           "health": dict(health),
           "entries": meta}
    extras = []
    if proc == 0:
        extras += [(HEALTH_STAMP_FILE, dict(health)),
                   ("scalars.json", scalars)]
    # the manifest lands LAST: its presence is this host's commit marker
    extras.append((f"metadata_{proc}.json", doc))
    for name, payload in extras:
        if name == f"metadata_{proc}.json":
            _fire("ckpt_pre_rename")
        p = os.path.join(final, name)
        tmp = os.path.join(final, ".tmp_" + name)
        with open(tmp, "w") as f:  # noqa: PTA002 -- manifest/sidecar write, writer thread only
            json.dump(payload, f)
        if fsync:
            _fsync_file(tmp)
        os.replace(tmp, p)  # noqa: PTA002 -- per-file atomic publish, writer thread only
    if fsync:
        _fsync_dir(final)
    _fire("ckpt_post_rename")


def _barrier():
    """All hosts reached this point (no-op in a single-process job). Only
    the *collectively called* sync commit path may use this — the async
    writer thread must stay barrier-free (host-local coalescing makes
    its schedule nondeterministic across hosts)."""
    from ...distributed.collective import barrier
    barrier()


def _commit_files(path: str, meta, blobs, scalars, health,
                  fsync: bool = True):
    """Land one materialized snapshot at ``path``: atomic dir swap when
    this process owns the whole checkpoint, cooperative per-host files
    when peers share the directory."""
    import jax
    if jax.process_count() > 1:
        _write_cooperative(path, meta, blobs, scalars, health, fsync=fsync)
    else:
        staging = path + STAGING_SUFFIX
        _write_staged(staging, meta, blobs, scalars, health, fsync=fsync)
        _publish(staging, path)


def commit_checkpoint(state, path: str, *, healthy: bool = True,
                      step: Optional[int] = None,
                      reason: Optional[str] = None,
                      fsync: bool = True):
    """Synchronous crash-consistent checkpoint commit.

    Same layout as :func:`~paddle_tpu.incubate.checkpoint.save_sharded`
    but published atomically in single-process jobs: stage → fsync → one
    ``os.replace`` (a re-save parks the previous commit as ``*.old``
    first, so there is never a zero-checkpoint instant). The health
    stamp rides inside the same commit (manifest ``health`` key + the
    ``health.json`` sidecar staged pre-rename), closing the
    stamp-after-rename window the sidecar-only protocol had.

    Multi-host jobs keep ``save_sharded``'s cooperative per-host-file
    protocol (see :func:`_write_cooperative` — a dir swap would destroy
    peer hosts' shards), with the health doc still inside the manifest;
    like ``save_sharded`` this is safe to call from every process, and a
    trailing barrier makes the return mean "checkpoint complete on all
    hosts".

    This is the cold-path entry (sentinel rollback snapshots, tests);
    the train loop uses :class:`AsyncCheckpointer`, whose writer thread
    lands in the same commit code (minus the barrier — writer schedules
    are host-local).
    """
    import jax
    with _otrace.span("checkpoint/commit", {"path": path}):
        from ...core.tensor import Tensor
        flat = {k: (v._data if isinstance(v, Tensor) else v)
                for k, v in _flatten(state).items()}
        _fire("ckpt_fetch")
        meta, blobs, scalars = _materialize(flat)
        health = _health_doc(healthy, step, reason)
        _commit_files(path, meta, blobs, scalars, health, fsync=fsync)
        if jax.process_count() > 1:
            _barrier()
    return path


def cleanup_stale_staging(root: str,
                          held: Optional[Set[str]] = None) -> List[str]:
    """Sweep swap debris under ``root`` from a writer killed mid-commit in
    a previous run: orphaned ``*.tmp`` staging dirs are removed (by
    definition uncommitted), and a parked ``*.old`` dir is *recovered* —
    renamed back into place when the crash landed inside the swap window
    (final missing: the parked dir is the only complete checkpoint left),
    removed when the final was republished. Also sweeps cooperative-commit
    debris: orphaned ``.tmp_*`` *files* inside committed checkpoint dirs,
    left by a host killed mid-write of its per-file stage (multi-host
    commits have no dir-level staging to rename away — see
    :func:`_write_cooperative`). Being a startup-only sweep, any such file
    is by definition from a dead cohort generation, never live staging.
    ``held`` protects paths a live writer still owns. Returns the removed
    paths. Startup-only by contract (checkpoint GC must never race an
    in-flight stage)."""
    removed: List[str] = []
    recovered = 0
    try:
        names = os.listdir(root)
    except OSError:
        return removed
    for name in names:
        full = os.path.join(root, name)
        if not os.path.isdir(full):
            continue
        if name.endswith(STAGING_SUFFIX):
            if held and full in held:
                continue
            shutil.rmtree(full, ignore_errors=True)  # noqa: PTA002 -- startup-only orphan sweep, never on the step path
            removed.append(full)
        elif name.endswith(OLD_SUFFIX):
            final = full[:-len(OLD_SUFFIX)]
            if held and (full in held or final in held):
                continue
            if os.path.isdir(final):
                # the new checkpoint made it: the parked dir is just debris
                shutil.rmtree(full, ignore_errors=True)  # noqa: PTA002 -- startup-only orphan sweep, never on the step path
                removed.append(full)
            else:
                # crash between parking the old commit and publishing the
                # new one — un-park it so the path stays restorable
                os.replace(full, final)  # noqa: PTA002 -- startup-only swap recovery, never on the step path
                recovered += 1
    staged_count = len(removed)
    # cooperative-commit debris: ``.tmp_shards_<proc>.npz`` /
    # ``.tmp_metadata_<proc>.json`` files a dead host left inside a shared
    # checkpoint dir. Readers never see them (every walk keys on the
    # shards_/metadata_ prefixes), but a re-formed cohort re-saving the
    # same path must not inherit a dead peer's stale stage.
    tmp_files = 0
    candidates = [root] + [
        os.path.join(root, n) for n in names
        if not n.endswith(STAGING_SUFFIX) and not n.endswith(OLD_SUFFIX)]
    for d in candidates:
        if held and d in held:
            continue
        try:
            inner = os.listdir(d)
        except (OSError, NotADirectoryError):
            continue
        for fn in inner:
            if not fn.startswith(".tmp_"):
                continue
            fp = os.path.join(d, fn)
            if not os.path.isfile(fp):
                continue
            try:
                os.unlink(fp)  # noqa: PTA002 -- startup-only orphan sweep, never on the step path
            except OSError:
                continue
            removed.append(fp)
            tmp_files += 1
    if staged_count:
        _monitor.stat_add("ckpt.async.stale_staging_cleaned", staged_count)
    if recovered:
        _monitor.stat_add("ckpt.async.parked_old_recovered", recovered)
    if tmp_files:
        _monitor.stat_add("ckpt.async.orphan_tmp_files_cleaned", tmp_files)
    return removed


class SaveTicket:
    """Handle for one enqueued snapshot. ``wait()`` blocks until the
    snapshot is committed, superseded, or degraded-skipped; ``error`` is
    the terminal exception of a degraded/failed save (never raised on the
    step path)."""

    __slots__ = ("path", "step", "_done", "committed", "superseded",
                 "error")

    def __init__(self, path: str, step: Optional[int]):
        self.path = path
        self.step = step
        self._done = threading.Event()
        self.committed = False
        self.superseded = False
        self.error: Optional[BaseException] = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        """True once the ticket reached a terminal state."""
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def _finish(self, *, committed: bool = False, superseded: bool = False,
                error: Optional[BaseException] = None):
        if self._done.is_set():
            # terminal states are write-once: a late failure (e.g. in an
            # on_commit callback) must not un-commit a published ticket
            return
        self.committed = committed
        self.superseded = superseded
        self.error = error
        self._done.set()


class _Pending:
    """One queued snapshot: captured refs + commit metadata."""

    __slots__ = ("flat", "path", "health", "on_commit", "ticket")

    def __init__(self, flat, path, health, on_commit, ticket):
        self.flat = flat
        self.path = path
        self.health = health
        self.on_commit = on_commit
        self.ticket = ticket


class AsyncCheckpointConfig:
    """Tunables for :class:`AsyncCheckpointer`."""

    def __init__(self, queue_depth: int = 2, max_attempts: int = 3,
                 backoff: float = 0.05, fsync: bool = True,
                 degrade_on_failure: bool = True):
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        self.queue_depth = int(queue_depth)
        self.max_attempts = max(1, int(max_attempts))
        self.backoff = float(backoff)
        self.fsync = bool(fsync)
        self.degrade_on_failure = bool(degrade_on_failure)


class AsyncCheckpointer:
    """Overlapped, crash-consistent checkpoint writer.

    ::

        ckpt = AsyncCheckpointer()
        for epoch in range(epochs):
            train_one_epoch(...)
            ckpt.save(state, f"{root}/epoch_{epoch}", step=epoch,
                      on_commit=lambda e=epoch: commit_status(e))
        ckpt.wait()     # or close(); SIGTERM paths drain the same way

    Lock discipline (PTA006): ``_pending``, ``_in_flight``, ``_closed``
    and ``_thread`` are only touched under ``self._cond``; commit work,
    tickets and callbacks run outside it.
    """

    def __init__(self, config: Optional[AsyncCheckpointConfig] = None,
                 registry: Optional[_monitor.StatRegistry] = None):
        self._config = config or AsyncCheckpointConfig()
        self._registry = registry or _monitor.default_registry()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: List[_Pending] = []
        self._in_flight: Optional[_Pending] = None
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    @property
    def config(self) -> AsyncCheckpointConfig:
        return self._config

    # -- step-path side (must never block on I/O or device fetch) ----------
    def save(self, state, path: str, *, step: Optional[int] = None,
             healthy: bool = True, reason: Optional[str] = None,
             on_commit: Optional[Callable[[], None]] = None) -> SaveTicket:
        """Enqueue one snapshot of ``state`` for background commit to
        ``path``. Takes donation-safe on-device copies and kicks off the
        device→host DMA (both non-blocking dispatches) — the caller may
        keep training immediately; later optimizer steps can neither
        mutate nor delete the captured buffers.

        Never raises for I/O trouble and never blocks on the queue: a
        full queue supersedes the oldest unwritten snapshot instead."""
        t0 = time.perf_counter()
        import jax
        import jax.numpy as jnp
        from ...core.tensor import Tensor
        # double-buffer: the snapshot must own its bytes — the fused
        # optimizer step DONATES param buffers (optimizer.py
        # donate_argnums), so an aliased stash would be deleted under the
        # writer. On accelerators that is an async on-device copy plus a
        # device->host DMA kick, both non-blocking; the CPU backend runs
        # those dispatches synchronously (two memcpys), so there the cheap
        # donation-safe snapshot is ONE direct host memcpy instead.
        on_cpu = jax.default_backend() == "cpu"
        flat = {}
        for k, v in _flatten(state).items():
            raw = v._data if isinstance(v, Tensor) else v
            if hasattr(raw, "copy_to_host_async"):
                if on_cpu:
                    raw = np.array(raw, copy=True)  # noqa: PTA002 -- CPU device memory IS host memory: one owned memcpy is the cheapest donation-safe snapshot (an on-device copy would dispatch synchronously here and cost two copies)
                else:
                    raw = jnp.array(raw, copy=True)
                    raw.copy_to_host_async()
            flat[k] = raw
        ticket = SaveTicket(path, step)
        item = _Pending(flat, path, _health_doc(healthy, step, reason),
                        on_commit, ticket)
        superseded: List[_Pending] = []
        with self._cond:
            if self._closed:
                raise RuntimeError("AsyncCheckpointer is closed")
            while len(self._pending) >= self._config.queue_depth:
                superseded.append(self._pending.pop(0))
            self._pending.append(item)
            self._ensure_writer_locked()
            depth = len(self._pending)
            self._cond.notify_all()
        for old in superseded:
            old.ticket._finish(superseded=True)
        reg = self._registry
        reg.add("ckpt.async.saves", 1)
        if superseded:
            reg.add("ckpt.async.superseded", len(superseded))
        reg.set("ckpt.async.queue_depth", depth)
        reg.observe("ckpt.async.enqueue_ms",
                    (time.perf_counter() - t0) * 1e3)
        return ticket

    def _ensure_writer_locked(self):
        """(Re)start the writer thread; caller holds ``self._cond``. A
        writer killed by an unexpected error is replaced on the next
        save rather than silently dropping every later snapshot."""
        if self._thread is not None and self._thread.is_alive():
            return
        if self._thread is not None:
            self._registry.add("ckpt.async.writer_restarts", 1)
        self._thread = threading.Thread(
            target=self._run, name="paddle-tpu-ckpt-writer", daemon=True)
        self._thread.start()

    # -- draining -----------------------------------------------------------
    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every enqueued snapshot reached a terminal state
        (committed, superseded, or degraded). The SIGTERM/preemption
        drain path: an in-flight commit always finishes before exit."""
        with self._cond:
            return self._cond.wait_for(
                lambda: not self._pending and self._in_flight is None,
                timeout)

    def close(self, timeout: Optional[float] = None):
        """Drain then stop the writer thread. Idempotent."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout)

    def held_paths(self) -> Set[str]:
        """Final+staging paths the writer still owns — checkpoint GC must
        skip these (a keep-budget sweep racing the writer would delete
        the snapshot it is about to publish)."""
        with self._cond:
            items = list(self._pending)
            if self._in_flight is not None:
                items.append(self._in_flight)
        out: Set[str] = set()
        for it in items:
            out.add(it.path)
            out.add(it.path + STAGING_SUFFIX)
            out.add(it.path + OLD_SUFFIX)  # transient during a re-save swap
        return out

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- writer thread ------------------------------------------------------
    def _run(self):
        clean_exit = False
        try:
            while True:
                with self._cond:
                    self._cond.wait_for(
                        lambda: self._pending or self._closed)  # noqa: PTA006 -- wait_for evaluates the predicate while holding self._lock (the Condition's lock)
                    if not self._pending:
                        if self._closed:
                            clean_exit = True
                            return
                        continue
                    item = self._pending.pop(0)
                    self._in_flight = item
                    self._registry.set("ckpt.async.queue_depth",
                                       len(self._pending))
                try:
                    self._process(item)
                finally:
                    with self._cond:
                        self._in_flight = None
                        self._cond.notify_all()
        finally:
            if not clean_exit:
                # unexpected writer death (anything except a drained
                # close) — post-mortem needs the event even when the
                # exception text is lost to the daemon-thread abyss
                self._registry.add("ckpt.async.writer_deaths", 1)
                _flight.record_event("ckpt_writer_death", {})
                _flight.dump_if_armed("ckpt_writer_death")

    def _process(self, item: _Pending):
        reg = self._registry
        staging = item.path + STAGING_SUFFIX
        try:
            with _otrace.span("checkpoint/async_write",
                              {"path": item.path}):
                t0 = time.perf_counter()
                _fire("ckpt_fetch")
                meta, blobs, scalars = _materialize(item.flat)
                t1 = time.perf_counter()
                reg.observe("ckpt.async.fetch_ms", (t1 - t0) * 1e3)
                retry_call(
                    self._stage_and_publish,
                    (item, meta, blobs, scalars),
                    max_attempts=self._config.max_attempts,
                    backoff=self._config.backoff,
                    retry_on=(OSError,),
                    on_retry=lambda a, e, p: (
                        reg.add("ckpt.async.retries", 1),
                        _flight.record_event(
                            "ckpt_retry",
                            {"path": item.path, "attempt": a,
                             "error": repr(e)})))
                t2 = time.perf_counter()
                reg.observe("ckpt.async.write_ms", (t2 - t1) * 1e3)
            reg.add("ckpt.async.commits", 1)
            item.ticket._finish(committed=True)
        except RetryError as e:
            shutil.rmtree(staging, ignore_errors=True)  # noqa: PTA002 -- degraded-path cleanup, writer thread only
            if not self._config.degrade_on_failure:
                item.ticket._finish(error=e)
                raise
            reg.add("ckpt.async.degraded_skips", 1)
            _flight.record_event("ckpt_degraded_skip",
                                 {"path": item.path, "error": repr(e)})
            _flight.dump_if_armed("ckpt_degraded_skip")
            warnings.warn(
                f"async checkpoint to {item.path} failed after "
                f"{self._config.max_attempts} attempts and was skipped "
                f"({e.__cause__!r}); training continues on the previous "
                f"committed checkpoint")
            item.ticket._finish(error=e)
            return
        except Exception as e:
            # non-I/O failure (a leaf that can't serialize, a bug): the
            # snapshot is lost but the writer and the train loop live on
            shutil.rmtree(staging, ignore_errors=True)  # noqa: PTA002 -- failure-path cleanup, writer thread only
            reg.add("ckpt.async.errors", 1)
            _flight.record_event("ckpt_error",
                                 {"path": item.path, "error": repr(e)})
            warnings.warn(f"async checkpoint to {item.path} failed: {e!r}")
            item.ticket._finish(error=e)
            return
        # the checkpoint is durably published at this point: a failing
        # post-commit callback gets its own accounting and must neither
        # look like a failed checkpoint nor disturb the committed ticket
        if item.on_commit is not None:
            try:
                item.on_commit()
            except Exception as e:
                reg.add("ckpt.async.on_commit_errors", 1)
                _flight.record_event("ckpt_on_commit_error",
                                     {"path": item.path, "error": repr(e)})
                warnings.warn(
                    f"on_commit callback for committed checkpoint "
                    f"{item.path} failed: {e!r}")

    def _stage_and_publish(self, item: _Pending, meta, blobs, scalars):
        t0 = time.perf_counter()
        _commit_files(item.path, meta, blobs, scalars, item.health,
                      fsync=self._config.fsync)
        self._registry.observe("ckpt.async.commit_ms",
                               (time.perf_counter() - t0) * 1e3)
