"""Epoch-range auto-checkpoint: restart-safe training loops.

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:71
(TrainEpochRange / AutoCheckpointChecker — wraps the epoch loop, snapshots
executor scope + epoch counters keyed by job id to HDFS, resumes after an
elastic restart; enabled by PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT,
job id from PADDLE_JOB_ID, storage from PADDLE_EDL_HDFS_CHECKPOINT_PATH).

TPU design: the snapshot is a sharded checkpoint (sharded.py) of the
registered model/optimizer state plus the epoch counter; storage goes
through the FS facade so a LocalFS path and an HDFS-shaped path behave the
same. A killed job rebuilt with the same name resumes at the next
unfinished epoch with identical state.

Fault-tolerance contract (docs/fault_tolerance.md): restore verifies shard
checksums and falls back to the newest *intact* committed epoch when the
referenced one is corrupt or half-deleted; orphaned partial epoch dirs
(from a crash mid-save) are garbage-collected at startup; under the elastic
launcher the epoch loop polls a PreemptionGuard and, on preemption, commits
a final checkpoint and exits with the reserved resume code.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import warnings
from typing import Dict, Optional

from .sharded import (save_sharded, load_sharded,
                      CheckpointIntegrityError, read_health_stamp,
                      write_health_stamp)
from .async_ckpt import AsyncCheckpointer, cleanup_stale_staging
from ...utils.resilience import fault_injector


def _default_root():
    return os.environ.get("PADDLE_EDL_HDFS_CHECKPOINT_PATH",
                          os.environ.get("PADDLE_CHECKPOINT_DIR",
                                         "./paddle_auto_checkpoint"))


def _job_id():
    return os.environ.get("PADDLE_JOB_ID", "default_job")


def _epoch_no(name: str) -> Optional[int]:
    """epoch_<N> -> N, or None for malformed names (stray tmp/partial dirs
    left by a crash must never abort the commit/GC path)."""
    suffix = name.split("_", 1)[1] if name.startswith("epoch_") else ""
    return int(suffix) if suffix.isdigit() else None


class TrainEpochRange:
    """Iterate epochs with automatic save/restore.

    ::

        r = TrainEpochRange(10, "job0", model=model, optimizer=opt)
        for epoch in r:
            train_one_epoch(...)
        # kill + rerun: the loop resumes at the first unfinished epoch
        # with model/optimizer state restored.

    Under ``launch --elastic`` (PADDLE_TPU_ELASTIC set) a PreemptionGuard is
    armed automatically: SIGTERM makes the loop commit a final checkpoint at
    the next epoch boundary and exit with PREEMPTION_EXIT_CODE, which the
    supervisor restarts without burning the restart budget. Pass
    ``preemption_guard=`` to share an explicitly-armed guard.
    """

    def __init__(self, max_epoch_num: int, name: Optional[str] = None,
                 model=None, optimizer=None, checkpoint_path: Optional[str] = None,
                 save_checkpoint_inter: int = 1, async_save: bool = False,
                 keep_last: int = 2, preemption_guard=None,
                 step_watchdog=None):
        self.max_epoch_num = int(max_epoch_num)
        self.name = name or _job_id()
        self._model = model
        self._optimizer = optimizer
        self._dir = os.path.join(checkpoint_path or _default_root(),
                                 self.name)
        self._inter = max(1, int(save_checkpoint_inter))
        self._keep_last = keep_last
        # async_save routes through the crash-consistent AsyncCheckpointer
        # (async_ckpt.py): overlapped fetch+write, atomic os.replace commit
        self._saver = AsyncCheckpointer() if async_save else None
        # mark_unhealthy verdicts for epochs whose async save is still
        # queued/in-flight; applied by _commit once the snapshot publishes
        self._unhealthy_lock = threading.Lock()
        self._pending_unhealthy: Dict[int, Optional[str]] = {}
        from ...distributed.elastic import maybe_auto_guard
        self._guard = maybe_auto_guard(preemption_guard)
        # collective watchdog (elastic_runtime): armed around each epoch
        # body the same way the PreemptionGuard is auto-armed — the cohort
        # supervisor sets PADDLE_TPU_STEP_DEADLINE_S in every child
        from ...distributed.elastic_runtime.watchdog import (
            maybe_auto_watchdog)
        self._watchdog = maybe_auto_watchdog(step_watchdog)
        self.restored_epoch = -1
        self._last_saved = -1
        # debris from a writer killed mid-stage in a previous run; startup
        # only, so this can never race our own in-flight saves
        cleanup_stale_staging(self._dir)
        self._restore()

    # -- persistence --------------------------------------------------------
    def _status_path(self):
        return os.path.join(self._dir, "status.json")

    def _epoch_dir(self, epoch):
        return os.path.join(self._dir, f"epoch_{epoch}")

    def _state(self):
        state = {}
        if self._model is not None:
            state["model"] = dict(self._model.state_dict())
        if self._optimizer is not None:
            state["optimizer"] = dict(self._optimizer.state_dict())
        return state

    def _committed_epoch(self) -> int:
        sp = self._status_path()
        if not os.path.exists(sp):
            return -1
        try:
            with open(sp) as f:
                status = json.load(f)
            return int(status.get("epoch_no", -1))
        except (ValueError, OSError):
            # torn status.json (should not happen: tmp+replace) — treat as
            # no commit rather than killing the restart
            return -1

    def _gc_orphans(self, committed: int):
        """Remove partial epoch dirs newer than the committed epoch — debris
        from a save that died before its commit (startup only, so this can
        never race an in-flight async save)."""
        if not os.path.isdir(self._dir):
            return
        for name in os.listdir(self._dir):
            e = _epoch_no(name)
            if e is not None and e > committed:
                shutil.rmtree(os.path.join(self._dir, name),
                              ignore_errors=True)

    def _restore(self):
        committed = self._committed_epoch()
        self._gc_orphans(committed)
        if committed < 0:
            return
        # newest intact committed epoch: the referenced one first, then any
        # older surviving epoch dirs (corruption/half-deletion fallback)
        candidates = sorted(
            {e for e in (_epoch_no(n) for n in os.listdir(self._dir))
             if e is not None and e <= committed},
            reverse=True)
        for epoch in candidates:
            ckpt = self._epoch_dir(epoch)
            if not os.path.isdir(ckpt):
                continue
            stamp = read_health_stamp(ckpt)
            if not stamp.get("healthy", True):
                # sentinel stamped this state numerically bad after it was
                # saved — integrity-intact but not worth resuming into
                warnings.warn(
                    f"auto_checkpoint: epoch {epoch} checkpoint at {ckpt} "
                    f"is stamped unhealthy "
                    f"({stamp.get('reason', 'no reason recorded')}); "
                    f"falling back to an older epoch")
                continue
            try:
                state = load_sharded(ckpt)
            except (CheckpointIntegrityError, OSError, ValueError,
                    KeyError) as e:
                warnings.warn(
                    f"auto_checkpoint: epoch {epoch} checkpoint at {ckpt} "
                    f"is not intact ({e}); falling back to an older epoch")
                continue
            if self._model is not None and "model" in state:
                self._model.set_state_dict(state["model"])
            if self._optimizer is not None and "optimizer" in state:
                self._optimizer.set_state_dict(state["optimizer"])
            self.restored_epoch = epoch
            self._last_saved = epoch
            return

    def mark_unhealthy(self, epoch: int, reason: Optional[str] = None):
        """Health-stamp an already-saved epoch as numerically bad (the
        sentinel detected the divergence only after the save); a restore
        will then skip it even though its checksums are intact. With
        ``async_save`` the epoch's snapshot may still be queued — the
        verdict is recorded and applied when the snapshot publishes."""
        ckpt = self._epoch_dir(epoch)
        if self._saver is not None:
            with self._unhealthy_lock:
                self._pending_unhealthy[epoch] = reason
        if os.path.isdir(ckpt):
            write_health_stamp(ckpt, False, step=epoch, reason=reason)
            if (self._saver is not None
                    and ckpt not in self._saver.held_paths()):
                # applied to the committed dir with nothing in flight that
                # could republish it — don't poison a future same-epoch save
                with self._unhealthy_lock:
                    self._pending_unhealthy.pop(epoch, None)

    def _commit(self, epoch: int):
        # a mark_unhealthy verdict that raced this epoch's in-flight save:
        # the snapshot just published with its save-time healthy stamp,
        # which the sentinel has since overruled
        with self._unhealthy_lock:
            pending = epoch in self._pending_unhealthy
            reason = self._pending_unhealthy.pop(epoch, None)
        if pending:
            write_health_stamp(self._epoch_dir(epoch), False, step=epoch,
                               reason=reason)
        # status.json is written only after the shard files exist, so a
        # crash mid-save leaves the previous checkpoint referenced; the
        # write itself is tmp+replace so a crash mid-write can't leave
        # truncated JSON (matching the shard files' atomic pattern). The
        # staging name is per-process: on a *shared* checkpoint dir every
        # host commits the same status (identical content, so concurrent
        # replaces are benign), but a shared tmp name is not — the first
        # host's replace consumes it and the others' replace raises
        # FileNotFoundError mid-commit. ``.tmp_`` prefix so a host killed
        # mid-write leaves debris the startup staging sweep removes.
        sp = self._status_path()
        tmp = os.path.join(self._dir, f".tmp_status_{os.getpid()}.json")
        with open(tmp, "w") as f:
            json.dump({"epoch_no": epoch, "max_epoch_num": self.max_epoch_num},
                      f)
        os.replace(tmp, sp)
        self._gc(epoch)

    def save(self, epoch: int):
        ckpt = self._epoch_dir(epoch)
        self._last_saved = epoch
        if self._saver is not None:
            # async: fetch+write AND the status commit happen on the writer
            # thread — training overlaps the whole save; a queue-full save
            # supersedes the older unwritten snapshot instead of blocking,
            # and status.json only ever references a published checkpoint
            # (on_commit fires strictly after the atomic os.replace)
            self._saver.save(self._state(), ckpt, step=epoch,
                             on_commit=lambda: self._commit(epoch))
        else:
            save_sharded(self._state(), ckpt)
            self._commit(epoch)

    def _gc(self, current):
        if self._keep_last is None:
            return
        held = self._saver.held_paths() if self._saver is not None else ()
        for name in os.listdir(self._dir):
            full = os.path.join(self._dir, name)
            if full in held:
                # the writer still owns this path (pending or staging) —
                # sweeping it would delete the snapshot about to publish
                continue
            e = _epoch_no(name)
            if e is None:
                continue
            if e <= current - self._keep_last * self._inter:
                shutil.rmtree(full, ignore_errors=True)

    # -- iteration ----------------------------------------------------------
    def get(self):
        return iter(self)

    def wait(self):
        if self._saver is not None:
            self._saver.wait()

    def _poll_preemption(self, epoch: int):
        if self._guard is None or not self._guard.preempted:
            return
        if self._last_saved < epoch:
            self.save(epoch)
        self.wait()  # the final checkpoint must be committed before exit
        self._guard.exit_if_preempted()

    def __iter__(self):
        try:
            for epoch in range(self.restored_epoch + 1, self.max_epoch_num):
                # fault site "epoch": PADDLE_TPU_FAULT_SPEC="epoch:N:crash"
                # hard-kills the Nth iteration of this process, mid-epoch
                # from the checkpoint's point of view
                fault_injector().fire("epoch")
                # the epoch body is the guarded step: a collective hung by
                # a peer death becomes exit 121 within the deadline instead
                # of stalling this loop forever
                if self._watchdog is not None:
                    self._watchdog.arm(epoch)
                yield epoch
                if self._watchdog is not None:
                    self._watchdog.disarm()
                if ((epoch + 1) % self._inter == 0
                        or epoch == self.max_epoch_num - 1):
                    self.save(epoch)
                self._poll_preemption(epoch)
        finally:
            if self._watchdog is not None:
                self._watchdog.disarm()
            self.wait()  # don't exit with an uncommitted in-flight save


def train_epoch_range(max_epoch_num, save_checkpoint_inter=1, **kw):
    """Function form (reference: auto_checkpoint.py train_epoch_range)."""
    return TrainEpochRange(max_epoch_num,
                           save_checkpoint_inter=save_checkpoint_inter, **kw)
