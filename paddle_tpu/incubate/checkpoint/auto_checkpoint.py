"""Epoch-range auto-checkpoint: restart-safe training loops.

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:71
(TrainEpochRange / AutoCheckpointChecker — wraps the epoch loop, snapshots
executor scope + epoch counters keyed by job id to HDFS, resumes after an
elastic restart; enabled by PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT,
job id from PADDLE_JOB_ID, storage from PADDLE_EDL_HDFS_CHECKPOINT_PATH).

TPU design: the snapshot is a sharded checkpoint (sharded.py) of the
registered model/optimizer state plus the epoch counter; storage goes
through the FS facade so a LocalFS path and an HDFS-shaped path behave the
same. A killed job rebuilt with the same name resumes at the next
unfinished epoch with identical state.
"""
from __future__ import annotations

import json
import os
import shutil
from typing import Optional

from .sharded import save_sharded, load_sharded, AsyncSaver


def _default_root():
    return os.environ.get("PADDLE_EDL_HDFS_CHECKPOINT_PATH",
                          os.environ.get("PADDLE_CHECKPOINT_DIR",
                                         "./paddle_auto_checkpoint"))


def _job_id():
    return os.environ.get("PADDLE_JOB_ID", "default_job")


class TrainEpochRange:
    """Iterate epochs with automatic save/restore.

    ::

        r = TrainEpochRange(10, "job0", model=model, optimizer=opt)
        for epoch in r:
            train_one_epoch(...)
        # kill + rerun: the loop resumes at the first unfinished epoch
        # with model/optimizer state restored.
    """

    def __init__(self, max_epoch_num: int, name: Optional[str] = None,
                 model=None, optimizer=None, checkpoint_path: Optional[str] = None,
                 save_checkpoint_inter: int = 1, async_save: bool = False,
                 keep_last: int = 2):
        self.max_epoch_num = int(max_epoch_num)
        self.name = name or _job_id()
        self._model = model
        self._optimizer = optimizer
        self._dir = os.path.join(checkpoint_path or _default_root(),
                                 self.name)
        self._inter = max(1, int(save_checkpoint_inter))
        self._keep_last = keep_last
        self._saver = AsyncSaver() if async_save else None
        self.restored_epoch = -1
        self._restore()

    # -- persistence --------------------------------------------------------
    def _status_path(self):
        return os.path.join(self._dir, "status.json")

    def _epoch_dir(self, epoch):
        return os.path.join(self._dir, f"epoch_{epoch}")

    def _state(self):
        state = {}
        if self._model is not None:
            state["model"] = dict(self._model.state_dict())
        if self._optimizer is not None:
            state["optimizer"] = dict(self._optimizer.state_dict())
        return state

    def _restore(self):
        sp = self._status_path()
        if not os.path.exists(sp):
            return
        with open(sp) as f:
            status = json.load(f)
        epoch = int(status.get("epoch_no", -1))
        if epoch < 0:
            return
        ckpt = self._epoch_dir(epoch)
        if not os.path.isdir(ckpt):
            return
        state = load_sharded(ckpt)
        if self._model is not None and "model" in state:
            self._model.set_state_dict(state["model"])
        if self._optimizer is not None and "optimizer" in state:
            self._optimizer.set_state_dict(state["optimizer"])
        self.restored_epoch = epoch

    def _commit(self, epoch: int):
        # status.json is written only after the shard files exist, so a
        # crash mid-save leaves the previous checkpoint referenced; the
        # write itself is tmp+replace so a crash mid-write can't leave
        # truncated JSON (matching the shard files' atomic pattern)
        sp = self._status_path()
        tmp = sp + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch_no": epoch, "max_epoch_num": self.max_epoch_num},
                      f)
        os.replace(tmp, sp)
        self._gc(epoch)

    def save(self, epoch: int):
        ckpt = self._epoch_dir(epoch)
        if self._saver is not None:
            # async: the fetch+write AND the status commit happen on the
            # background thread — training overlaps the whole save, and
            # AsyncSaver.save waits for any previous in-flight save first
            self._saver.save(self._state(), ckpt,
                             on_done=lambda: self._commit(epoch))
        else:
            save_sharded(self._state(), ckpt)
            self._commit(epoch)

    def _gc(self, current):
        if self._keep_last is None:
            return
        for name in os.listdir(self._dir):
            if not name.startswith("epoch_"):
                continue
            e = int(name.split("_", 1)[1])
            if e <= current - self._keep_last * self._inter:
                shutil.rmtree(os.path.join(self._dir, name),
                              ignore_errors=True)

    # -- iteration ----------------------------------------------------------
    def get(self):
        return iter(self)

    def wait(self):
        if self._saver is not None:
            self._saver.wait()

    def __iter__(self):
        try:
            for epoch in range(self.restored_epoch + 1, self.max_epoch_num):
                yield epoch
                if ((epoch + 1) % self._inter == 0
                        or epoch == self.max_epoch_num - 1):
                    self.save(epoch)
        finally:
            self.wait()  # don't exit with an uncommitted in-flight save


def train_epoch_range(max_epoch_num, save_checkpoint_inter=1, **kw):
    """Function form (reference: auto_checkpoint.py train_epoch_range)."""
    return TrainEpochRange(max_epoch_num,
                           save_checkpoint_inter=save_checkpoint_inter, **kw)
