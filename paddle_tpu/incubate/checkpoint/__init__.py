"""Sharded / async / auto checkpointing for multichip training.

Reference:
- auto-checkpoint: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:71
  (TrainEpochRange — epoch-range loop that snapshots state keyed by job id
  and resumes after a restart; EDL hooks)
- saver: incubate/checkpoint/checkpoint_saver.py
- PS sharded tables: distributed/common/sparse_sharding_merge.h

TPU design: a checkpoint is a directory of per-host shard files + a JSON
metadata index. Each host writes only the array shards it can address
(``jax.Array.addressable_shards``), so a multi-host job writes in parallel
with no cross-host traffic; load reassembles the global arrays and
re-shards them onto the *current* mesh (which may have a different
topology — resharding on restore). Async mode moves the device→host fetch
and file write off the training thread (the orbax-style pattern);
``async_ckpt`` is the crash-consistent flavor: bounded-queue coalescing
double-buffered snapshots published by a single atomic ``os.replace``
(docs/fault_tolerance.md, "Async checkpointing").
"""
from .sharded import (save_sharded, load_sharded, AsyncSaver,  # noqa: F401
                      CheckpointIntegrityError, verify_checkpoint,
                      HEALTH_STAMP_FILE, OLD_SUFFIX, STAGING_SUFFIX,
                      write_health_stamp, read_health_stamp,
                      newest_healthy_checkpoint, swap_eligible)
from .async_ckpt import (AsyncCheckpointer, AsyncCheckpointConfig,  # noqa: F401
                         CommitError, SaveTicket, commit_checkpoint,
                         cleanup_stale_staging)
from .auto_checkpoint import TrainEpochRange, train_epoch_range  # noqa: F401
