"""paddle.incubate: graduated-API staging area (reference:
python/paddle/fluid/incubate/)."""
from . import checkpoint  # noqa: F401
from ..ops.segment import (segment_sum, segment_mean, segment_max,  # noqa: F401
                           segment_min, segment_pool)
