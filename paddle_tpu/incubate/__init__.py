"""paddle.incubate: graduated-API staging area (reference:
python/paddle/fluid/incubate/)."""
from . import checkpoint  # noqa: F401
from ..ops.segment import (segment_sum, segment_mean, segment_max,  # noqa: F401
                           segment_min, segment_pool)
from . import optimizer  # noqa: F401


class LayerHelper:
    """reference: fluid/layer_helper.py LayerHelper — the fluid-era
    program-building helper custom ops used to append ops/vars by hand.
    There is no Program being appended to here; custom ops register via
    ops.custom.register_custom_op / register_pallas_op instead."""

    def __init__(self, *a, **k):
        raise RuntimeError(
            "LayerHelper is a fluid-era program builder; define custom "
            "computation with paddle_tpu.ops.custom.register_custom_op "
            "(host/numpy tier) or register_pallas_op (TPU kernel tier)")
