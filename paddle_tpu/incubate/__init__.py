"""paddle.incubate: graduated-API staging area (reference:
python/paddle/fluid/incubate/)."""
from . import checkpoint  # noqa: F401
