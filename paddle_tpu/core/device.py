"""Device / Place abstraction over the XLA (PjRt) client.

TPU-native replacement for the reference platform layer
(reference: paddle/fluid/platform/place.h `Place` variant and
platform/device_context.h:796 `DeviceContextPool`). Streams, events and
communicator handles are owned by XLA — the framework only names devices.

`Place` mirrors paddle's CPUPlace/CUDAPlace API shape with TPUPlace first-class.
`set_device`/`get_device` mirror python/paddle/device/__init__.py.
"""
from __future__ import annotations

import jax


class Place:
    """A named device slot: device_type + device_id."""

    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = int(device_id)

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_tpu_place(self):
        return self.device_type in ("tpu", "axon")

    def jax_device(self):
        """Resolve to the backing jax.Device."""
        devs = jax.devices()
        if self.device_type == "cpu":
            try:
                devs = jax.devices("cpu")
            except RuntimeError:
                pass
        if self.device_id < len(devs):
            return devs[self.device_id]
        return devs[0]


def CPUPlace():
    return Place("cpu", 0)


def TPUPlace(dev_id: int = 0):
    return Place("tpu", dev_id)


# CUDAPlace exists for API-compat of ported scripts; it resolves to the default
# accelerator (reference code that says CUDAPlace(i) means "accelerator i").
def CUDAPlace(dev_id: int = 0):
    return Place(_default_backend(), dev_id)


_CURRENT = [None]


def _default_backend() -> str:
    return jax.default_backend()


def set_device(device: str) -> Place:
    """paddle.set_device parity. Accepts 'cpu', 'tpu', 'tpu:0', 'gpu:0' (→ accelerator)."""
    if ":" in device:
        kind, _, idx = device.partition(":")
        idx = int(idx)
    else:
        kind, idx = device, 0
    if kind == "gpu":  # ported-script compat: gpu means "the accelerator"
        kind = _default_backend()
    place = Place(kind, idx)
    _CURRENT[0] = place
    return place


def get_device() -> str:
    p = _expected_place()
    return f"{p.device_type}:{p.device_id}"


def _expected_place() -> Place:
    if _CURRENT[0] is None:
        _CURRENT[0] = Place(_default_backend(), 0)
    return _CURRENT[0]


def device_count(kind: str = None) -> int:
    try:
        return len(jax.devices(kind)) if kind else len(jax.devices())
    except RuntimeError:
        return 0


def is_compiled_with_cuda() -> bool:
    """API parity helper; always False — zero CUDA symbols linked."""
    return False


def is_compiled_with_tpu() -> bool:
    return True


def synchronize(place: Place = None):
    """Block until all dispatched work on the device is done
    (reference: DeviceContext::Wait). XLA: realized via blocking on arrays;
    here we use the effects barrier."""
    (jax.device_put(0) + 0).block_until_ready()
