"""Auditable-entrypoint registry for the trace-level analyzer.

The AST tier of ``tools.analyze`` sees source text; the trace tier
(PTA009/PTA010) needs *programs*: the actual step functions the framework
jits, plus representative arguments to trace them with. Runtime modules
register those here at import time — cheaply, as lazy factories — and
``tools/analyze/trace`` imports this module (under ``JAX_PLATFORMS=cpu``)
to enumerate them.

An entrypoint factory returns an :class:`AuditSpec`:

- ``fn`` — the RAW (un-jitted) python step function. The auditor wraps it
  in its own counting ``jax.jit`` so trace counts are observable.
- ``make_args(variant)`` — builds a FRESH tuple of positional arguments
  for the call. ``variant`` (0 or 1) must perturb array *values* but keep
  every shape/dtype/static identical: a correct entrypoint traces once
  across variants; a retrace is a PTA010 finding. Fresh arrays per call
  matter because ``jit_kwargs`` may donate input buffers.
- ``jit_kwargs`` — the kwargs production code passes to ``jax.jit``
  (``donate_argnums``, ``static_argnums``, ...), so the audited program
  is the deployed program.
- ``tags`` — e.g. ``("train",)`` enables the donated-buffer-opportunity
  check; ``("serving",)`` marks latency paths.

Registration is import-time metadata only: nothing is built until the
auditor calls the factory, so production imports stay fast.
"""
from __future__ import annotations

import inspect
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple


@dataclass
class AuditSpec:
    """One concrete auditable program, built lazily by a factory."""
    fn: Callable
    make_args: Callable[[int], tuple]
    jit_kwargs: Dict[str, Any] = field(default_factory=dict)


@dataclass(frozen=True)
class AuditEntrypoint:
    name: str
    factory: Callable[[], AuditSpec]
    tags: Tuple[str, ...] = ()
    path: str = ""    # repo-relative posix path of the registration site
    line: int = 0

    def build(self) -> AuditSpec:
        return self.factory()


_REGISTRY: Dict[str, AuditEntrypoint] = {}


def _site_of(factory) -> Tuple[str, int]:
    """repo-relative path + line of the factory definition, so trace
    findings anchor to the code that registered the entrypoint."""
    try:
        src = inspect.getsourcefile(factory)
        line = factory.__code__.co_firstlineno
    except (TypeError, AttributeError):
        return "", 0
    if not src:
        return "", 0
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    rel = os.path.relpath(os.path.abspath(src), root)
    return rel.replace(os.sep, "/"), line


def register_entrypoint(name: str, factory: Callable[[], AuditSpec],
                        tags: Tuple[str, ...] = (),
                        path: Optional[str] = None,
                        line: Optional[int] = None) -> None:
    """Idempotent: re-registering a name replaces the entry (module
    reloads in tests)."""
    auto_path, auto_line = _site_of(factory)
    _REGISTRY[name] = AuditEntrypoint(
        name=name, factory=factory, tags=tuple(tags),
        path=path if path is not None else auto_path,
        line=line if line is not None else auto_line)


def entrypoints() -> Dict[str, AuditEntrypoint]:
    return dict(_REGISTRY)


def load_default_entrypoints() -> Dict[str, AuditEntrypoint]:
    """Import every module that registers an auditable entrypoint and
    return the populated registry. Safe to call repeatedly."""
    # each import triggers the module-level register_entrypoint() calls
    from ..hapi import model as _hapi_model            # noqa: F401
    from ..static import executor as _executor         # noqa: F401
    from ..serving import engine as _engine            # noqa: F401
    from ..serving.llm import decode as _decode        # noqa: F401
    from ..serving.llm import spec as _spec            # noqa: F401
    from ..serving.llm.paged import decode as _paged_decode  # noqa: F401
    from ..models import bench_audit as _bench_audit   # noqa: F401
    from ..distributed import collective as _coll      # noqa: F401
    from ..distributed.fleet import audit_specs as _fleet_specs  # noqa: F401
    return entrypoints()
