"""Random generator: global + per-name RNG state.

TPU-native equivalent of the reference Generator
(reference: paddle/fluid/framework/generator.cc, python/paddle/fluid/generator.py,
`paddle.seed`). On TPU randomness is functional: a Generator owns a JAX PRNG key
and hands out split subkeys; compiled code threads keys explicitly.

Also hosts the RNG state-tracker used for parallel dropout determinism
(reference: fleet/meta_parallel/parallel_layers/random.py RNGStatesTracker).
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np
import jax


class Generator:
    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._key = jax.random.PRNGKey(self._seed)
        self._lock = threading.Lock()

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.PRNGKey(self._seed)
        return self

    seed = manual_seed

    def initial_seed(self) -> int:
        return self._seed

    def split(self, n: int = 1):
        """Return n fresh subkeys, advancing the state."""
        with self._lock:
            keys = jax.random.split(self._key, n + 1)
            self._key = keys[0]
            return keys[1] if n == 1 else keys[1:]

    def get_state(self):
        return np.asarray(self._key)

    def set_state(self, state):
        self._key = jax.numpy.asarray(state, dtype=jax.numpy.uint32)


_DEFAULT = Generator(0)
_NUMPY_SEEDED = [False]

# While a functionalization trace is active (paddle_tpu/jit/functionalize.py),
# key draws are rerouted through the trace's key argument so compiled programs
# get fresh randomness per call instead of a baked-in constant key.
_TRACE_HOOK = [None]


def default_generator() -> Generator:
    return _DEFAULT


def seed(value: int) -> Generator:
    """paddle.seed parity (reference: framework/generator.cc seeds all device
    generators; here one functional key feeds all devices)."""
    _DEFAULT.manual_seed(value)
    np.random.seed(value & 0xFFFFFFFF)
    _NUMPY_SEEDED[0] = True
    return _DEFAULT


def next_key():
    if _TRACE_HOOK[0] is not None:
        return _TRACE_HOOK[0]()
    return _DEFAULT.split(1)


def get_rng_state():
    return _DEFAULT.get_state()


def set_rng_state(state):
    _DEFAULT.set_state(state)


class RNGStatesTracker:
    """Named RNG states so e.g. tensor-parallel dropout can be identical inside
    a TP group but different across DP ranks
    (reference: fleet/meta_parallel/parallel_layers/random.py:30)."""

    def __init__(self):
        self._states = {}

    def add(self, name: str, seed_value: int):
        if name in self._states:
            raise ValueError(f"RNG state {name} already exists")
        self._states[name] = Generator(seed_value)

    def reset(self):
        self._states = {}

    @contextlib.contextmanager
    def rng_state(self, name: str):
        if name not in self._states:
            raise KeyError(f"RNG state {name} not registered")
        global _DEFAULT
        prev = _DEFAULT
        _DEFAULT = self._states[name]
        try:
            yield
        finally:
            _DEFAULT = prev


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _TRACKER
