"""ctypes binding for the C++ shared-memory ring (csrc/shm_ring.cpp) —
the native DataLoader transport (reference:
memory/allocation/mmap_allocator.cc + reader/buffered_reader.cc).

Builds the .so on first use with the system g++ (cached under
csrc/build/); environments without a toolchain fall back to queue
transport in the DataLoader (``available()`` is the gate).
"""
from __future__ import annotations

import ctypes
import os
import pickle
import struct
import subprocess
import threading

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "build", "libshm_ring.so")
_LIB = None
_BUILD_LOCK = threading.Lock()


def _build():
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    src = os.path.join(_CSRC, "shm_ring.cpp")
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o",
           _SO + ".tmp", src, "-lrt", "-lpthread"]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(_SO + ".tmp", _SO)


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None:
            return _LIB
        src = os.path.join(_CSRC, "shm_ring.cpp")
        stale = (os.path.exists(_SO) and os.path.exists(src)
                 and os.path.getmtime(_SO) < os.path.getmtime(src))
        if not os.path.exists(_SO) or stale:
            _build()
        lib = ctypes.CDLL(_SO)
        lib.shm_ring_create.restype = ctypes.c_void_p
        lib.shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.shm_ring_open.restype = ctypes.c_void_p
        lib.shm_ring_open.argtypes = [ctypes.c_char_p]
        lib.shm_ring_push.restype = ctypes.c_int
        lib.shm_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int64, ctypes.c_int64]
        lib.shm_ring_pop.restype = ctypes.c_int
        lib.shm_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_int64, ctypes.c_int64]
        lib.shm_ring_capacity.restype = ctypes.c_int64
        lib.shm_ring_capacity.argtypes = [ctypes.c_void_p]
        lib.shm_ring_used.restype = ctypes.c_int64
        lib.shm_ring_used.argtypes = [ctypes.c_void_p]
        lib.shm_ring_close.argtypes = [ctypes.c_void_p]
        lib.shm_ring_unlink.argtypes = [ctypes.c_char_p]
        _LIB = lib
        return lib


def available() -> bool:
    if os.name != "posix":
        return False
    try:
        _load()
        return True
    except Exception:
        return False


class ShmRing:
    """Single-producer single-consumer byte ring in POSIX shared memory."""

    def __init__(self, name: str, capacity: int = 64 << 20,
                 create: bool = True):
        self._lib = _load()
        self.name = name.encode()
        if create:
            self._base = self._lib.shm_ring_create(self.name, capacity)
        else:
            self._base = self._lib.shm_ring_open(self.name)
        if not self._base:
            raise OSError(f"shm_ring {'create' if create else 'open'} "
                          f"failed for {name}")
        self._creator = create

    def push_bytes(self, data: bytes, timeout_ms: int = 120_000):
        rc = self._lib.shm_ring_push(self._base, data, len(data), timeout_ms)
        if rc == -2:
            raise ValueError(f"payload of {len(data)}B exceeds ring "
                             f"capacity; raise DataLoader shm capacity")
        if rc != 0:
            raise TimeoutError("shm_ring push timed out (consumer stalled)")

    def pop_bytes(self, n: int, timeout_ms: int = 120_000) -> bytes:
        buf = ctypes.create_string_buffer(n)
        rc = self._lib.shm_ring_pop(self._base, buf, n, timeout_ms)
        if rc != 0:
            raise TimeoutError("shm_ring pop timed out (producer stalled)")
        return buf.raw

    # -- pickled-object transport (protocol-5 out-of-band buffers) ---------
    def push_object(self, obj, timeout_ms: int = 120_000) -> int:
        """Returns total bytes pushed; the caller ships that count through
        its metadata channel so the consumer knows how much to pop."""
        buffers = []
        payload = pickle.dumps(obj, protocol=5,
                               buffer_callback=buffers.append)
        parts = [payload] + [bytes(b.raw()) for b in buffers]
        header = struct.pack("<q", len(parts)) + b"".join(
            struct.pack("<q", len(p)) for p in parts)
        blob = header + b"".join(parts)
        self.push_bytes(blob, timeout_ms)
        return len(blob)

    def pop_object(self, total: int, timeout_ms: int = 120_000):
        blob = self.pop_bytes(total, timeout_ms)
        (n_parts,) = struct.unpack_from("<q", blob, 0)
        sizes = struct.unpack_from(f"<{n_parts}q", blob, 8)
        off = 8 + 8 * n_parts
        parts = []
        for s in sizes:
            parts.append(blob[off:off + s])
            off += s
        return pickle.loads(parts[0], buffers=parts[1:])

    def close(self):
        if self._base:
            self._lib.shm_ring_close(self._base)
            self._base = None
        if self._creator:
            self._lib.shm_ring_unlink(self.name)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
