"""Dtype model.

TPU-native replacement for the reference's VarType dtype enum
(reference: paddle/fluid/framework/framework.proto:106 `VarType.Type`) and the
fp16/bf16 types (reference: paddle/fluid/platform/float16.h, bfloat16.h).
On TPU, dtypes are just numpy/jax dtypes; bfloat16 is first-class (MXU native),
float16 is supported but bf16 is preferred.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# Canonical dtype objects (numpy dtype instances; jnp accepts them directly).
bool_ = np.dtype("bool")
uint8 = np.dtype("uint8")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
float16 = np.dtype("float16")
bfloat16 = jnp.bfloat16  # numpy extension dtype via ml_dtypes
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_ALIASES = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "fp16": float16,
    "bfloat16": np.dtype(bfloat16),
    "bf16": np.dtype(bfloat16),
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "fp64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_DEFAULT_DTYPE = [float32]


def convert_dtype(dtype):
    """Normalize a user dtype spec (str | np.dtype | jnp dtype | None) to np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower().replace("paddle.", "")
        if key in _ALIASES:
            return np.dtype(_ALIASES[key])
        return np.dtype(key)
    return np.dtype(dtype)


def set_default_dtype(dtype):
    """paddle.set_default_dtype parity (reference: python/paddle/framework/framework.py)."""
    d = convert_dtype(dtype)
    if d not in (np.dtype("float16"), np.dtype(bfloat16), float32, float64):
        raise TypeError(
            "set_default_dtype only supports float16/bfloat16/float32/float64, got %s" % d
        )
    _DEFAULT_DTYPE[0] = d


def get_default_dtype():
    return _DEFAULT_DTYPE[0]


def is_floating(dtype) -> bool:
    d = convert_dtype(dtype)
    return np.issubdtype(d, np.floating) or d == np.dtype(bfloat16)


def is_integer(dtype) -> bool:
    return np.issubdtype(convert_dtype(dtype), np.integer)


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    return d.name
