"""Named stats gauges (reference: paddle/fluid/platform/monitor.h:77
StatRegistry, STAT_ADD :130 — int/float gauges e.g. device memory stats).

TPU note: device memory accounting lives with XLA; `device_memory_stats`
surfaces what the backend reports, and the generic registry is available
to any subsystem (io workers, checkpointing, launcher) for counters.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Tuple, Union

Number = Union[int, float]

#: sorted ``(key, value)`` pairs — the canonical identity of one labeled
#: time series (insertion-order-insensitive, hashable).
LabelSet = Tuple[Tuple[str, str], ...]

#: default reservoir bound per histogram — old samples roll off so quantiles
#: track recent behaviour (a sliding window, not all-time).
DEFAULT_HIST_SAMPLES = 2048


class _Histogram:
    """Bounded-reservoir value distribution (count/total are all-time;
    quantiles come from the newest ``maxlen`` samples).

    Thread-safety contract: _Histogram has no lock of its own. Every
    instance is owned by exactly one StatRegistry, which creates it and
    calls ``observe``/``summary``/``quantile`` strictly inside
    ``self._lock`` — the count/total/vmin/vmax updates in ``observe`` are
    not atomic individually, but the registry lock makes the whole method
    a critical section. Do not hand instances out past the registry."""

    __slots__ = ("count", "total", "vmin", "vmax", "samples")

    def __init__(self, max_samples: int = DEFAULT_HIST_SAMPLES):
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.samples = deque(maxlen=max_samples)

    def observe(self, value: Number):
        v = float(value)  # noqa: PTA001 -- monitor samples are host-side scalars by contract (never called under trace; the name-collision is with an unrelated `.observe`)
        self.count += 1
        self.total += v
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)
        self.samples.append(v)

    def quantile(self, q: float) -> float:
        if not self.samples:
            return 0.0
        xs = sorted(self.samples)
        if len(xs) == 1:
            return xs[0]
        # linear interpolation between closest ranks
        pos = min(max(q, 0.0), 1.0) * (len(xs) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(xs) - 1)
        return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)

    def summary(self) -> Dict[str, float]:
        xs = sorted(self.samples)

        def _q(q):
            if not xs:
                return 0.0
            pos = q * (len(xs) - 1)
            lo = int(pos)
            hi = min(lo + 1, len(xs) - 1)
            return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)

        return {
            "count": self.count,
            "sum": self.total,
            "min": self.vmin if self.count else 0.0,
            "max": self.vmax if self.count else 0.0,
            "mean": (self.total / self.count) if self.count else 0.0,
            "p50": _q(0.50),
            "p95": _q(0.95),
            "p99": _q(0.99),
        }


class StatRegistry:
    """reference: platform/monitor.h:77."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, Number] = {}
        self._hists: Dict[str, _Histogram] = {}
        # labeled gauges: name -> {sorted (k, v) label tuple -> value}
        self._labeled: Dict[str, Dict[LabelSet, Number]] = {}
        # exposition kind per scalar stat: "counter" (add) | "gauge" (set).
        # First writer wins so a stat that is both add()ed and set() keeps a
        # stable TYPE line across scrapes.
        self._kinds: Dict[str, str] = {}

    def add(self, name: str, value: Number) -> Number:
        with self._lock:
            self._stats[name] = self._stats.get(name, 0) + value
            self._kinds.setdefault(name, "counter")
            return self._stats[name]

    def set(self, name: str, value: Number):
        with self._lock:
            self._stats[name] = value
            self._kinds.setdefault(name, "gauge")

    def get(self, name: str, default: Number = 0) -> Number:
        with self._lock:
            return self._stats.get(name, default)

    def set_labeled(self, name: str, labels: Dict[str, str], value: Number):
        """Gauge with label dimensions (one time series per label set),
        e.g. ``set_labeled("serving.llm.slot_state", {"state": "busy"}, 3)``.
        Labels are normalized to a sorted tuple so insertion order never
        forks a series."""
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        with self._lock:
            self._labeled.setdefault(name, {})[key] = value

    def labeled(self, name: str) -> Dict[LabelSet, Number]:
        with self._lock:
            return dict(self._labeled.get(name, {}))

    def reset(self, name: str = None):
        with self._lock:
            if name is None:
                self._stats.clear()
                self._hists.clear()
                self._labeled.clear()
                self._kinds.clear()
            else:
                self._stats.pop(name, None)
                self._hists.pop(name, None)
                self._labeled.pop(name, None)
                self._kinds.pop(name, None)

    def stats(self) -> Dict[str, Number]:
        with self._lock:
            return dict(self._stats)

    def stats_with_prefix(self, prefix: str) -> Dict[str, Number]:
        """All counters/gauges under a dotted namespace (e.g. ``sentinel.``,
        ``amp.``) — the dashboard-scrape shape for one subsystem."""
        with self._lock:
            return {k: v for k, v in self._stats.items()
                    if k.startswith(prefix)}

    # -- histograms ---------------------------------------------------------
    def observe(self, name: str, value: Number,
                max_samples: int = DEFAULT_HIST_SAMPLES):
        """Record one sample of a value distribution (latency, fill ratio).
        Bounded memory: quantiles reflect the newest ``max_samples``."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram(max_samples)
            h.observe(value)

    def quantile(self, name: str, q: float, default: float = 0.0) -> float:
        with self._lock:
            h = self._hists.get(name)
            return h.quantile(q) if h is not None else default

    def histogram(self, name: str) -> Dict[str, float]:
        """Summary dict (count/sum/min/max/mean/p50/p95/p99); zeros if the
        histogram has never been observed."""
        with self._lock:
            h = self._hists.get(name)
            return h.summary() if h is not None else _Histogram(1).summary()

    def histograms(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: h.summary() for k, h in self._hists.items()}

    def histograms_with_prefix(self, prefix: str) -> Dict[str, Dict[str, float]]:
        """Histogram summaries under a dotted namespace (``serving.llm.``…)
        — the /statsz shape for one subsystem's distributions."""
        with self._lock:
            return {k: h.summary() for k, h in self._hists.items()
                    if k.startswith(prefix)}

    def snapshot(self) -> Dict[str, Dict]:
        """One internally-consistent view of every stat, histogram, labeled
        series and kind, taken under a *single* lock acquisition.

        ``stats()`` + ``histograms()`` back-to-back each lock separately, so
        a concurrent ``observe``/``add`` between the two calls yields a
        counter that disagrees with its histogram (e.g. ``requests`` ==
        hist count + 1). Exposition (/metricsz, print_stats, flight dumps)
        must use this instead."""
        with self._lock:
            return {
                "stats": dict(self._stats),
                "kinds": dict(self._kinds),
                "histograms": {k: h.summary()
                               for k, h in self._hists.items()},
                "labeled": {k: dict(v) for k, v in self._labeled.items()},
            }

    def print_stats(self):
        snap = self.snapshot()
        for k, v in sorted(snap["stats"].items()):
            print(f"STAT {k} = {v}")
        for k, s in sorted(snap["histograms"].items()):
            print(f"HIST {k} = count={s['count']} p50={s['p50']:.6g} "
                  f"p95={s['p95']:.6g} p99={s['p99']:.6g}")


_REGISTRY = StatRegistry()


def default_registry() -> StatRegistry:
    return _REGISTRY


def stat_add(name: str, value: Number) -> Number:
    """reference: monitor.h:130 STAT_ADD."""
    return _REGISTRY.add(name, value)


def stat_set(name: str, value: Number):
    _REGISTRY.set(name, value)


def stat_get(name: str, default: Number = 0) -> Number:
    return _REGISTRY.get(name, default)


def stat_set_labeled(name: str, labels: Dict[str, str], value: Number):
    """Labeled gauge on the default registry (one series per label set)."""
    _REGISTRY.set_labeled(name, labels, value)


def stat_observe(name: str, value: Number,
                 max_samples: int = DEFAULT_HIST_SAMPLES):
    """Record a histogram sample on the default registry (bounded memory)."""
    _REGISTRY.observe(name, value, max_samples)


def stat_quantile(name: str, q: float, default: float = 0.0) -> float:
    """q-quantile (0..1) of a histogram's recent samples, or ``default``."""
    return _REGISTRY.quantile(name, q, default)


def stats_with_prefix(prefix: str) -> Dict[str, Number]:
    """Default-registry view of one subsystem's counters (``sentinel.``…)."""
    return _REGISTRY.stats_with_prefix(prefix)


def histograms_with_prefix(prefix: str) -> Dict[str, Dict[str, float]]:
    """Default-registry view of one subsystem's histogram summaries."""
    return _REGISTRY.histograms_with_prefix(prefix)


def device_memory_stats(device=None) -> Dict[str, Number]:
    """Per-device memory stats as reported by the backend (the reference
    tracks these via its own allocator; XLA owns allocation here)."""
    import jax
    d = device or jax.devices()[0]
    try:
        s = d.memory_stats() or {}
    except Exception:
        s = {}
    return {k: v for k, v in s.items() if isinstance(v, (int, float))}
