"""Named stats gauges (reference: paddle/fluid/platform/monitor.h:77
StatRegistry, STAT_ADD :130 — int/float gauges e.g. device memory stats).

TPU note: device memory accounting lives with XLA; `device_memory_stats`
surfaces what the backend reports, and the generic registry is available
to any subsystem (io workers, checkpointing, launcher) for counters.
"""
from __future__ import annotations

import threading
from typing import Dict, Union

Number = Union[int, float]


class StatRegistry:
    """reference: platform/monitor.h:77."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, Number] = {}

    def add(self, name: str, value: Number) -> Number:
        with self._lock:
            self._stats[name] = self._stats.get(name, 0) + value
            return self._stats[name]

    def set(self, name: str, value: Number):
        with self._lock:
            self._stats[name] = value

    def get(self, name: str, default: Number = 0) -> Number:
        with self._lock:
            return self._stats.get(name, default)

    def reset(self, name: str = None):
        with self._lock:
            if name is None:
                self._stats.clear()
            else:
                self._stats.pop(name, None)

    def stats(self) -> Dict[str, Number]:
        with self._lock:
            return dict(self._stats)

    def print_stats(self):
        for k, v in sorted(self.stats().items()):
            print(f"STAT {k} = {v}")


_REGISTRY = StatRegistry()


def default_registry() -> StatRegistry:
    return _REGISTRY


def stat_add(name: str, value: Number) -> Number:
    """reference: monitor.h:130 STAT_ADD."""
    return _REGISTRY.add(name, value)


def stat_set(name: str, value: Number):
    _REGISTRY.set(name, value)


def stat_get(name: str, default: Number = 0) -> Number:
    return _REGISTRY.get(name, default)


def device_memory_stats(device=None) -> Dict[str, Number]:
    """Per-device memory stats as reported by the backend (the reference
    tracks these via its own allocator; XLA owns allocation here)."""
    import jax
    d = device or jax.devices()[0]
    try:
        s = d.memory_stats() or {}
    except Exception:
        s = {}
    return {k: v for k, v in s.items() if isinstance(v, (int, float))}
