"""Structured error types + enforce helpers.

TPU-native equivalent of the reference's PADDLE_ENFORCE / PADDLE_THROW machinery
(reference: paddle/fluid/platform/enforce.h:427, errors.h, error_codes.proto).
The reference attaches the op-creation Python stack to runtime errors
(framework/op_call_stack.cc); here errors are raised directly from Python so the
traceback is native.
"""
from __future__ import annotations


class EnforceNotMet(RuntimeError):
    """Base framework error (reference: enforce.h EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet):
    pass


class FatalError(EnforceNotMet):
    pass


def enforce(cond, msg, *args, exc=InvalidArgumentError):
    """PADDLE_ENFORCE(cond, fmt, ...) parity (reference: enforce.h:427)."""
    if not cond:
        raise exc(msg % args if args else msg)


def enforce_eq(a, b, msg="", exc=InvalidArgumentError):
    if a != b:
        raise exc(f"Expected {a!r} == {b!r}. {msg}")


def enforce_shape_match(shape_a, shape_b, msg=""):
    if tuple(shape_a) != tuple(shape_b):
        raise InvalidArgumentError(f"Shape mismatch: {tuple(shape_a)} vs {tuple(shape_b)}. {msg}")


def throw(msg, *args, exc=EnforceNotMet):
    """PADDLE_THROW parity (reference: enforce.h:415)."""
    raise exc(msg % args if args else msg)
