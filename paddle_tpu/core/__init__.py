"""Core runtime: dtypes, flags, errors, device, RNG, Tensor, autograd.

TPU-native replacement for the reference's platform + memory + imperative C++
layers (SURVEY §1 L0-L3). Device memory, streams and collectives are owned by
the XLA runtime; this layer owns naming, dispatch policy, the tape, RNG state
and configuration.
"""
from .dtypes import (bool_, uint8, int8, int16, int32, int64, float16,
                     bfloat16, float32, float64, complex64, complex128,
                     set_default_dtype, get_default_dtype, convert_dtype)
from .flags import set_flags, get_flags, define_flag, flag_value
from .errors import (EnforceNotMet, InvalidArgumentError, NotFoundError,
                     UnimplementedError, enforce, throw)
from .device import (Place, CPUPlace, CUDAPlace, TPUPlace, set_device,
                     get_device, device_count, is_compiled_with_cuda,
                     is_compiled_with_tpu, synchronize)
from .generator import (Generator, seed, default_generator, get_rng_state,
                        set_rng_state, get_rng_state_tracker)
from .tensor import Tensor, Parameter
from .autograd_engine import (no_grad, enable_grad, is_grad_enabled,
                              set_grad_enabled, backward, grad)
