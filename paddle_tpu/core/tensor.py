"""The eager Tensor: a mutable named holder over an immutable jax.Array.

TPU-native equivalent of the reference's VarBase + Tensor
(reference: paddle/fluid/imperative/layer.h:66 `VarBase`,
framework/tensor.h:89 `Tensor`, framework/tensor.h:77 `TensorInplaceVersion`).

Paddle semantics preserved:
- ``stop_gradient`` defaults True for data, set False for parameters
- ``t.grad`` accumulated by ``loss.backward()``; ``clear_grad()`` resets
- in-place-looking APIs (``set_value``, ``__setitem__``) swap the underlying
  immutable array and bump ``_inplace_version`` (the reference guards autograd
  against in-place races the same way).

Math/manipulation methods are attached by ``paddle_tpu.ops`` at import time
(the reference attaches them via generated pybind ``core.ops``; here it is a
method-patch table, see ops/__init__.py).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import dtypes as _dtypes
from .device import Place, _expected_place
from . import autograd_engine as _ag


class Tensor:
    __slots__ = ("_data", "stop_gradient", "_grad", "_grad_node", "name",
                 "persistable", "_inplace_version", "_backward_hooks",
                 "_hook_counter", "trainable", "__weakref__", "is_distributed",
                 "_sharding_spec", "_uid")

    def __init__(self, data, dtype=None, place=None, stop_gradient=True,
                 name=None, persistable=False):
        if isinstance(data, Tensor):
            data = data._data
        dtype = _dtypes.convert_dtype(dtype)
        if isinstance(data, (jax.Array, jax.core.Tracer)):
            self._data = data.astype(dtype) if (dtype is not None and data.dtype != dtype) else data
        else:
            arr = np.asarray(data)
            if dtype is None and arr.dtype == np.float64:
                dtype = _dtypes.get_default_dtype()  # paddle default-dtype convention
            self._data = jnp.asarray(arr, dtype=dtype)
        self.stop_gradient = bool(stop_gradient)
        self._grad = None
        self._grad_node = None
        self.name = name
        self.persistable = persistable
        self._inplace_version = 0
        self._backward_hooks = None
        self._hook_counter = 0
        self.trainable = not stop_gradient
        self.is_distributed = False
        self._sharding_spec = None

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self) -> Place:
        d = getattr(self._data, "devices", None)
        if d:
            dev = next(iter(self._data.devices()))
            return Place(dev.platform, dev.id)
        return _expected_place()

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def grad(self):
        if self._grad is None:
            return None
        return Tensor(self._grad, stop_gradient=True)

    @grad.setter
    def grad(self, value):
        self._grad = None if value is None else (
            value._data if isinstance(value, Tensor) else jnp.asarray(value))

    def _accumulate_grad(self, g):
        # reference: imperative/gradient_accumulator.cc (sum accumulation)
        if g.dtype != self._data.dtype:
            g = g.astype(self._data.dtype)
        self._grad = g if self._grad is None else self._grad + g

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        g = None
        if grad_tensor is not None:
            g = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
        _ag.backward(self, g, retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        """Gradient hook (reference: imperative/hooks.h); returns a removable
        handle."""
        if self._backward_hooks is None:
            self._backward_hooks = {}
        hid = self._hook_counter
        self._hook_counter += 1
        self._backward_hooks[hid] = hook
        tensor = self

        class _Handle:
            def remove(self):
                tensor._backward_hooks.pop(hid, None)
        return _Handle()

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def requires_grad_(self, value: bool = True):
        self.stop_gradient = not value
        return self

    # -- conversion ---------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)  # noqa: PTA001 -- numpy() IS the eager materialization API; never called under trace

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()  # noqa: PTA001 -- eager materialization API by contract

    def astype(self, dtype) -> "Tensor":
        from ..ops.dispatch import apply
        d = _dtypes.convert_dtype(dtype)
        return apply("cast", lambda x: x.astype(d), self)

    cast = astype

    def clone(self) -> "Tensor":
        from ..ops.dispatch import apply
        return apply("clone", lambda x: x + 0, self)

    def cpu(self):
        return Tensor(np.asarray(self._data), stop_gradient=self.stop_gradient)

    def pin_memory(self):
        # host staging is XLA's job on TPU; identity is the honest behavior
        return self

    _DEVICE_PREFIXES = ("cpu", "gpu", "xpu", "npu", "tpu", "ipu")

    def to(self, *args, **kwargs):
        """paddle.Tensor.to(dtype) / to(device) / to(device, dtype):
        dtype strings/objects really cast (a ported ``.to('float64')``
        must not silently stay float32); device moves return self on the
        single-backend runtime — preserving the autograd chain and the
        Parameter identity. Unrecognized strings raise (a dtype typo must
        not silently become a device no-op)."""
        dtype = kwargs.get("dtype")
        device = kwargs.get("device")
        for a in args:
            if isinstance(a, str):
                try:
                    dt = _dtypes.convert_dtype(a)
                except (KeyError, TypeError, ValueError):
                    dt = None
                if dt is not None:
                    dtype = a
                elif a.split(":")[0] in Tensor._DEVICE_PREFIXES:
                    device = a
                else:
                    raise ValueError(
                        f"Tensor.to: {a!r} is neither a known dtype nor a "
                        f"device (cpu/gpu/xpu[:N])")
            elif isinstance(a, (np.dtype, type)):
                dtype = a
            elif isinstance(a, Place):
                device = a
        del device  # placement is XLA's job here; .to(device) is identity
        if dtype is not None:
            return self.astype(dtype)
        return self

    def value(self):
        return self

    def get_tensor(self):
        return self

    # -- mutation (in-place style) -----------------------------------------
    def set_value(self, value):
        """Replace contents in place (reference: VarBase SetValue); bumps the
        inplace version like TensorInplaceVersion (tensor.h:77).

        Under a functionalization trace (jit.to_static) a traced value is
        captured as a state effect instead of mutating the holder; under
        static-graph mode a symbolic Variable value is registered with the
        Program the same way."""
        if type(value).__name__ == "Variable" and hasattr(value, "_program"):
            value._program.record_state_effect(self, value)
            return
        raw = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        if isinstance(raw, jax.core.Tracer):
            from ..jit.functionalize import active_trace
            ctx = active_trace()
            if ctx is not None:
                if tuple(raw.shape) != tuple(self._data.shape):
                    raise ValueError(
                        f"set_value shape mismatch under trace: "
                        f"{tuple(raw.shape)} vs {tuple(self._data.shape)}")
                ctx.record_effect(self, raw.astype(self._data.dtype))
                return
        if tuple(raw.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {tuple(raw.shape)} vs {tuple(self._data.shape)}")
        self._data = raw.astype(self._data.dtype)
        self._inplace_version += 1
        self._grad_node = None

    def copy_(self, other, blocking=True):
        self.set_value(other)
        return self

    def _swap_payload(self, other: "Tensor"):
        """Adopt another tensor's data + tape node (functional in-place).

        Deliberately does NOT bump _inplace_version: this path is tape-recorded
        (reshape_, relu_, __setitem__, increment), so downstream consumers get
        correct gradients through the recorded node — the version guard is for
        raw, untaped replacement (set_value)."""
        self._data = other._data
        self._grad_node = other._grad_node

    def __setitem__(self, idx, value):
        from ..ops.dispatch import apply
        raw_idx = _unwrap_index(idx)

        def _fit(x, v):
            # jnp's .at[].set broadcasts but cannot drop dims; paddle/numpy
            # allow assigning shape-(1,) to a scalar slot — squeeze leading 1s.
            target = jax.eval_shape(lambda t: t[raw_idx], x).shape
            while v.ndim > len(target) and v.shape[0] == 1:
                v = v.reshape(v.shape[1:])
            return x.at[raw_idx].set(v.astype(x.dtype))

        if isinstance(value, Tensor):
            out = apply("set_value", _fit, self, value)
        else:
            out = apply("set_value",
                        lambda x: _fit(x, jnp.asarray(value)), self)
        self._swap_payload(out)

    def __getitem__(self, idx):
        from ..ops.dispatch import apply
        raw_idx = _unwrap_index(idx)
        if _index_has_tensor(idx):
            # advanced indexing with tensor indices participates in autograd
            return apply("getitem", lambda x, *i: x[_rebuild_index(raw_idx, i)],
                         self, *_index_tensors(idx))
        return apply("getitem", lambda x: x[raw_idx], self)

    # -- misc ---------------------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        grad_s = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}{grad_s},\n"
                f"       {np.asarray(self._data)!r})")

    def __bool__(self):
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.numpy().item(), spec)
        return repr(self)

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    # jax pytree integration: Tensor flattens to its raw array
    def __jax_array__(self):
        return self._data

    def block_until_ready(self):
        if hasattr(self._data, "block_until_ready"):
            self._data.block_until_ready()
        return self


def _unwrap_index(idx):
    if isinstance(idx, tuple):
        return tuple(i._data if isinstance(i, Tensor) else i for i in idx)
    return idx._data if isinstance(idx, Tensor) else idx


def _index_has_tensor(idx):
    if isinstance(idx, Tensor):
        return True
    if isinstance(idx, tuple):
        return any(isinstance(i, Tensor) for i in idx)
    return False


def _index_tensors(idx):
    if isinstance(idx, Tensor):
        return (idx,)
    return tuple(i for i in idx if isinstance(i, Tensor))


def _rebuild_index(raw_idx, tensor_raws):
    """Substitute traced index arrays back into the index structure."""
    it = iter(tensor_raws)
    if not isinstance(raw_idx, tuple):
        return next(it) if isinstance(raw_idx, (jax.Array, jax.core.Tracer)) else raw_idx
    out = []
    for i in raw_idx:
        out.append(next(it) if isinstance(i, (jax.Array, jax.core.Tracer)) else i)
    return tuple(out)


def _as_raw(x):
    return x._data if isinstance(x, Tensor) else x


# Parameter: a trainable Tensor (reference: python/paddle/fluid/framework.py:5400
# ParamBase — a VarBase with trainable/regularizer attributes).
class Parameter(Tensor):
    __slots__ = ("optimize_attr", "regularizer", "do_model_average", "need_clip",
                 "is_distributed_param")

    def __init__(self, data, dtype=None, name=None, trainable=True, **kw):
        super().__init__(data, dtype=dtype, name=name, stop_gradient=not trainable,
                         persistable=True)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = kw.get("regularizer")
        self.do_model_average = kw.get("do_model_average")
        self.need_clip = kw.get("need_clip", True)
        self.is_distributed_param = False

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


_UID_COUNTER = iter(range(1, 2 ** 62))


def stable_uid(t: Tensor) -> int:
    """Process-unique id for a Tensor, assigned lazily on first use.

    Unlike ``id()``, never reused after the object is garbage-collected —
    optimizer accumulators keyed by it can't silently alias a new
    Parameter that CPython placed at a recycled address."""
    try:
        return t._uid
    except AttributeError:
        t._uid = next(_UID_COUNTER)
        return t._uid
