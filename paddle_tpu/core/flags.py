"""Global flag registry with FLAGS_* environment override.

TPU-native equivalent of the reference's gflags globals
(reference: paddle/fluid/platform/flags.cc — 35 DEFINE_*;
pybind/global_value_getter_setter.cc exposes them as paddle.set_flags/get_flags).
We keep the FLAGS_<name> env contract: any registered flag can be preset via the
environment at import time and changed at runtime with set_flags().
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict

_REGISTRY: Dict[str, "_Flag"] = {}


class _Flag:
    __slots__ = ("name", "value", "default", "help", "caster", "on_change")

    def __init__(self, name, default, help_str, caster, on_change=None):
        self.name = name
        self.default = default
        self.help = help_str
        self.caster = caster
        self.on_change = on_change
        env = os.environ.get("FLAGS_" + name)
        self.value = caster(env) if env is not None else default


def _cast_bool(v):
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def define_flag(name: str, default: Any, help_str: str = "",
                caster: Callable = None, on_change: Callable = None):
    if caster is None:
        if isinstance(default, bool):
            caster = _cast_bool
        elif isinstance(default, int):
            caster = int
        elif isinstance(default, float):
            caster = float
        else:
            caster = str
    _REGISTRY[name] = _Flag(name, default, help_str, caster, on_change)
    return _REGISTRY[name]


def get_flags(flags):
    """paddle.get_flags parity. Accepts a name or list of names."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for n in flags:
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _REGISTRY:
            raise ValueError("Unknown flag: %s" % n)
        out["FLAGS_" + key] = _REGISTRY[key].value
    return out


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags parity."""
    for n, v in flags.items():
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _REGISTRY:
            raise ValueError("Unknown flag: %s" % n)
        f = _REGISTRY[key]
        f.value = f.caster(v)
        if f.on_change is not None:
            f.on_change(f.value)


def flag_value(name: str):
    return _REGISTRY[name].value


# ---------------------------------------------------------------------------
# Core flags (subset of reference Appendix E relevant on TPU).
define_flag("check_nan_inf", False,
            "After every eager op, scan outputs for NaN/Inf and raise "
            "(reference: platform/flags.cc FLAGS_check_nan_inf + "
            "framework/details/nan_inf_utils_detail.cc:411).")
define_flag("paddle_num_threads", 1, "Host-side intra-op threads (XLA-CPU).")
define_flag("cudnn_deterministic", False,
            "Deterministic kernels; on TPU maps to XLA deterministic reductions.")
define_flag("selected_devices", "",
            "Comma-separated local device ids (reference FLAGS_selected_gpus).")
define_flag("benchmark", False, "Emit per-step benchmark logs.")
define_flag("sort_sum_gradient", False,
            "Deterministic gradient accumulation order in the tape engine "
            "(reference: imperative/flags.cc).")
define_flag("fraction_of_gpu_memory_to_use", 0.92,
            "Kept for API parity; HBM is managed by the XLA runtime.")
define_flag("eager_delete_tensor_gb", 0.0, "Kept for API parity; GC is Python/XLA-owned.")
define_flag("tpu_donate_buffers", True,
            "Donate param/opt-state buffers in compiled train steps (in-place update).")
define_flag("log_level", 0, "Framework VLOG-style verbosity (reference GLOG_v).")
