"""Tape autograd engine for eager (dygraph) mode.

TPU-native equivalent of the reference imperative engine:
- tape recording        (reference: paddle/fluid/imperative/tracer.cc:207 CreateGradOpNode)
- reverse walk          (reference: imperative/basic_engine.cc:235 PrepareDeps, :305 Execute)
- grad accumulation     (reference: imperative/gradient_accumulator.cc)

Design difference from the reference: instead of per-op hand-written grad
kernels selected via GradOpMaker, every eager op is executed through
``jax.vjp`` of its (traceable) jnp implementation, so the backward of each op
is an XLA-compiled computation and coverage is automatic for every op. When a
whole forward is wrapped by ``jit.to_static`` the entire model becomes ONE tape
node whose vjp is a single compiled HLO — the per-op tape is the debug path,
exactly matching the reference's dygraph-slow / static-fast split (SURVEY §7).
"""
from __future__ import annotations

import contextlib
import functools
from collections import defaultdict, deque
from typing import Any, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

_GRAD_ENABLED = [True]


def is_grad_enabled() -> bool:
    return _GRAD_ENABLED[0]


def set_grad_enabled(mode: bool):
    _GRAD_ENABLED[0] = bool(mode)


class no_grad:
    """Context manager + decorator disabling tape recording
    (reference: python/paddle/fluid/dygraph/base.py no_grad_)."""

    def __enter__(self):
        self._prev = _GRAD_ENABLED[0]
        _GRAD_ENABLED[0] = False
        return self

    def __exit__(self, *exc):
        _GRAD_ENABLED[0] = self._prev
        return False

    def __call__(self, func):
        @functools.wraps(func)
        def wrapper(*a, **k):
            with no_grad():
                return func(*a, **k)
        return wrapper


class enable_grad(no_grad):
    def __enter__(self):
        self._prev = _GRAD_ENABLED[0]
        _GRAD_ENABLED[0] = True
        return self


class InputRef:
    """Producer binding of one differentiable input, captured at record time.

    The Python Tensor object is mutable (set_value/__setitem__ rebind its data
    and node), so the tape must remember which GradNode produced the value
    that was *consumed*, not whatever the object points at later. The version
    snapshot detects in-place mutation of leaves needed for backward
    (reference: framework/tensor.h:77 TensorInplaceVersion, checked in
    basic_engine.cc)."""

    __slots__ = ("tensor", "node", "idx", "version")

    def __init__(self, tensor):
        self.tensor = tensor
        entry = getattr(tensor, "_grad_node", None)
        if entry is None:
            self.node, self.idx = None, None
        else:
            self.node, self.idx = entry
        self.version = tensor._inplace_version


def _is_inexact(dtype):
    return np.issubdtype(np.dtype(dtype), np.inexact) or dtype == jnp.bfloat16


class GradNode:
    """One recorded op on the tape. Holds the vjp closure (residuals live in
    device memory until backward frees them) and the differentiable input
    bindings (reference: imperative/op_base.h:182 GradOpNode).

    ``replay`` is ``(pure_fn, other_raws)`` where
    ``pure_fn(diff_raws, other_raws) -> out_leaves`` re-executes the op's
    primal as a function of the differentiable inputs — the double-grad
    path re-derives the vjp from it under a fresh trace so second-order
    dependence on the primals is tracked (the reference keeps a dedicated
    engine for this, imperative/partial_grad_engine.cc)."""

    __slots__ = ("name", "vjp_fn", "inputs", "out_avals", "accum", "replay",
                 "__weakref__")

    def __init__(self, name: str, vjp_fn, inputs: List, out_avals: List,
                 replay=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = [InputRef(t) for t in inputs]
        self.out_avals = out_avals    # [(shape, dtype)] for every output leaf
        self.accum: dict = {}         # out leaf index -> accumulated cotangent
        self.replay = replay

    def seed(self, idx: int, g):
        if idx in self.accum:
            self.accum[idx] = self.accum[idx] + g
        else:
            self.accum[idx] = g

    def cotangents(self):
        """Materialize the full output-cotangent structure; zeros where no
        gradient flowed (reference: basic_engine fills empty grads with zeros)."""
        cots = []
        for i, (shape, dtype) in enumerate(self.out_avals):
            if i in self.accum:
                cots.append(self.accum[i])
            elif _is_inexact(dtype):
                cots.append(jnp.zeros(shape, dtype))
            else:
                # integer/bool outputs take symbolic zero cotangents
                cots.append(np.zeros(shape, dtype=jax.dtypes.float0))
        return cots


def _node_of(t) -> Optional[Tuple[GradNode, int]]:
    return getattr(t, "_grad_node", None)


def _run_hooks(t, g):
    hooks = getattr(t, "_backward_hooks", None)
    if hooks:
        for h in list(hooks.values()):
            out = h(g)
            if out is not None:
                g = out if not hasattr(out, "_data") else out._data
    return g


def _replay_node(node: "GradNode"):
    """Re-derive and run the node's vjp as a *recorded op*, so the computed
    cotangents carry their own tape (double grad). The vjp is rebuilt from
    the primal inputs under a fresh trace — second-order dependence on the
    primals is tracked, unlike calling the stored vjp closure whose
    residuals are baked constants."""
    from .tensor import Tensor
    from ..ops.dispatch import apply

    custom = getattr(node, "py_replay", None)
    if custom is not None:  # PyLayer: the user backward IS the grad program
        return custom()

    if node.replay is None:
        raise NotImplementedError(
            f"create_graph=True through op '{node.name}' is not supported: "
            f"the op recorded no replayable primal")
    pure2, other_raws = node.replay
    inexact_ix = [i for i, (s, d) in enumerate(node.out_avals)
                  if _is_inexact(d)]
    inexact_set = set(inexact_ix)
    cots = node.cotangents()
    cot_args = [cots[i] if isinstance(cots[i], Tensor) else Tensor(cots[i])
                for i in inexact_ix]
    for ref in node.inputs:
        if ref.tensor._inplace_version != ref.version:
            raise RuntimeError(
                f"Tensor needed for the double-grad of op '{node.name}' was "
                f"modified in place (version {ref.version} -> "
                f"{ref.tensor._inplace_version})")
    prim = [ref.tensor for ref in node.inputs]
    n_prim = len(prim)
    avals = list(node.out_avals)

    def raw_fn(*raws):
        p, c_in = raws[:n_prim], raws[n_prim:]
        it = iter(c_in)
        full = []
        for i, (s, d) in enumerate(avals):
            if i in inexact_set:
                full.append(next(it))
            else:
                full.append(np.zeros(s, dtype=jax.dtypes.float0))
        _, vjp2 = jax.vjp(lambda *dd: pure2(dd, other_raws), *p)
        return vjp2(tuple(full))

    out = apply(node.name + "_grad", raw_fn, *prim, *cot_args)
    return list(out) if isinstance(out, (list, tuple)) else [out]


def _execute(roots, retain_graph: bool = False, watched: Optional[dict] = None,
             create_graph: bool = False):
    """Queue-driven reverse-topological tape walk over possibly multiple
    seeded roots (reference: imperative/basic_engine.cc:305 Execute).

    ``roots`` is a list of (tensor, grad-or-None). When ``watched`` is given
    (a dict keyed by id(tensor)), cotangents arriving at those tensors are
    accumulated there and leaf ``.grad`` fields are left untouched —
    functional `paddle.grad` mode (reference: partial_grad_engine.cc).
    With ``create_graph`` every node's vjp runs through the op funnel
    (see _replay_node) so the results are differentiable again.
    """
    from .tensor import Tensor

    root_nodes = []
    for root, grad in roots:
        entry = _node_of(root)
        if entry is None:
            continue  # leaf with no graph: nothing to do (matches dygraph)
        root_node, root_idx = entry
        if grad is None:
            shape, dtype = root_node.out_avals[root_idx]
            grad = jnp.ones(shape, dtype)
        if create_graph and not isinstance(grad, Tensor):
            grad = Tensor(grad)
        root_node.seed(root_idx, grad)
        root_nodes.append(root_node)
        if watched is not None and id(root) in watched:
            watched[id(root)].append(grad)
    if not root_nodes:
        return

    # PrepareDeps: BFS from the roots counting consumer edges per reachable
    # node (reference: basic_engine.cc:235).
    indeg = defaultdict(int)
    seen = set()
    stack = []
    for rn in root_nodes:
        if id(rn) not in seen:
            seen.add(id(rn))
            stack.append(rn)
    while stack:
        n = stack.pop()
        for ref in n.inputs:
            if ref.node is None:
                continue
            indeg[id(ref.node)] += 1
            if id(ref.node) not in seen:
                seen.add(id(ref.node))
                stack.append(ref.node)

    queue = deque(rn for rn in dict.fromkeys(root_nodes) if indeg[id(rn)] == 0)
    while queue:
        node = queue.popleft()
        if node.vjp_fn is None:
            raise RuntimeError(
                f"Trying to backward through op '{node.name}' a second time; "
                "set retain_graph=True to allow this.")
        # apply() arranges every op's pure fn to return a flat tuple of output
        # leaves, so the cotangent is always a tuple.
        if create_graph:
            in_cots = _replay_node(node)
        else:
            in_cots = node.vjp_fn(tuple(node.cotangents()))
        if not retain_graph:
            node.vjp_fn = None
        node.accum = {}
        for ref, g in zip(node.inputs, in_cots):
            t = ref.tensor
            g = _run_hooks(t, g)
            if watched is not None and id(t) in watched and ref.version == t._inplace_version:
                watched[id(t)].append(g)
            if ref.node is not None:
                ref.node.seed(ref.idx, g)
                indeg[id(ref.node)] -= 1
                if indeg[id(ref.node)] == 0:
                    queue.append(ref.node)
            elif watched is None and not t.stop_gradient:
                if t._inplace_version != ref.version:
                    raise RuntimeError(
                        f"Tensor needed for the backward of op '{node.name}' "
                        f"was modified in place (version {ref.version} -> "
                        f"{t._inplace_version}); this would produce wrong "
                        "gradients (reference: TensorInplaceVersion guard).")
                t._accumulate_grad(g)


def backward(root, grad=None, retain_graph: bool = False):
    _execute([(root, grad)], retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """Functional ``paddle.grad`` (reference: imperative/partial_grad_engine.cc
    via python/paddle/fluid/dygraph/base.py grad). With ``create_graph=True``
    the returned gradients carry their own tape, so they can be
    differentiated again (gradient penalties, double grad — the reference's
    PartialGradEngine with create_graph)."""
    from .tensor import Tensor

    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    grad_outputs = [g for g in (grad_outputs if isinstance(grad_outputs, (list, tuple))
                                else [grad_outputs])]

    # Collect mode: one multi-root walk; leaf .grad fields are untouched and
    # intermediate (non-leaf) inputs get their cotangents too.
    watched = {id(t): [] for t in inputs}
    if create_graph:
        roots = [(o, g) for o, g in zip(outputs, grad_outputs)]
        retain = True if retain_graph is None else bool(retain_graph)
    else:
        roots = [(o, None if g is None
                  else (g._data if isinstance(g, Tensor) else g))
                 for o, g in zip(outputs, grad_outputs)]
        retain = bool(retain_graph)
    _execute(roots, retain_graph=retain, watched=watched,
             create_graph=create_graph)

    results = []
    for t in inputs:
        contribs = watched[id(t)]
        if not contribs:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused; "
                    "pass allow_unused=True to return None for it.")
            results.append(None)
        elif create_graph:
            total = contribs[0]
            for c in contribs[1:]:
                total = total + c
            results.append(total if isinstance(total, Tensor)
                           else Tensor(total))
        else:
            total = contribs[0]
            for c in contribs[1:]:
                total = total + c
            results.append(Tensor(total, stop_gradient=True))
    return results
