"""paddle.metric parity (reference: python/paddle/metric/metrics.py —
Metric base, Accuracy, Precision, Recall, Auc; plus functional accuracy)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..ops.dispatch import apply_raw


def _np(x):
    return np.asarray(x._data) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-processing running on device; default passthrough."""
        return args


class Accuracy(Metric):
    """reference: metrics.py Accuracy (top-k)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim:  # one-hot or [N,1]
            if label_np.shape[-1] == pred_np.shape[-1]:
                label_np = label_np.argmax(-1)
            else:
                label_np = label_np.squeeze(-1)
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = topk_idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        res = []
        for i, k in enumerate(self.topk):
            num = float(c[..., :k].sum())
            self.total[i] += num
            self.count[i] += c.shape[0] if c.ndim > 1 else len(c)
            res.append(num / max(c.shape[0] if c.ndim > 1 else len(c), 1))
        return np.asarray(res[0] if len(res) == 1 else res)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """reference: metrics.py Precision (binary)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """reference: metrics.py Auc (threshold-bucketed ROC AUC)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = _np(labels).reshape(-1)
        buckets = np.minimum((p * self.num_thresholds).astype(np.int64),
                             self.num_thresholds)
        for b, y in zip(buckets, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over cumulative TPR/FPR from high threshold to low
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional accuracy op (reference: operators/metrics/accuracy_op)."""
    import jax.numpy as jnp

    def impl(p, l):
        topk = jnp.argsort(-p, axis=-1)[..., :k]
        ll = l.reshape(-1, 1)
        return jnp.mean(jnp.any(topk == ll, axis=-1).astype(jnp.float32))
    return apply_raw("accuracy", impl, input, label)
