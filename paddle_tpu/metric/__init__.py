"""paddle.metric parity (reference: python/paddle/metric/metrics.py —
Metric base, Accuracy, Precision, Recall, Auc; plus functional accuracy)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..ops.dispatch import apply_raw


def _np(x):
    return np.asarray(x._data) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-processing running on device; default passthrough."""
        return args


class Accuracy(Metric):
    """reference: metrics.py Accuracy (top-k)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim:  # one-hot or [N,1]
            if label_np.shape[-1] == pred_np.shape[-1]:
                label_np = label_np.argmax(-1)
            else:
                label_np = label_np.squeeze(-1)
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = topk_idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        res = []
        for i, k in enumerate(self.topk):
            num = float(c[..., :k].sum())
            self.total[i] += num
            self.count[i] += c.shape[0] if c.ndim > 1 else len(c)
            res.append(num / max(c.shape[0] if c.ndim > 1 else len(c), 1))
        return np.asarray(res[0] if len(res) == 1 else res)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """reference: metrics.py Precision (binary)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """reference: metrics.py Auc (threshold-bucketed ROC AUC)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = _np(labels).reshape(-1)
        buckets = np.minimum((p * self.num_thresholds).astype(np.int64),
                             self.num_thresholds)
        for b, y in zip(buckets, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over cumulative TPR/FPR from high threshold to low
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional accuracy op (reference: operators/metrics/accuracy_op)."""
    import jax.numpy as jnp

    def impl(p, l):
        topk = jnp.argsort(-p, axis=-1)[..., :k]
        ll = l.reshape(-1, 1)
        return jnp.mean(jnp.any(topk == ll, axis=-1).astype(jnp.float32))
    return apply_raw("accuracy", impl, input, label)


class DetectionMAP(Metric):
    """VOC-style detection mAP (reference: fluid/metrics.py DetectionMAP
    over operators/detection/detection_map_op.cc).

    The reference accumulates matched TP/FP inside a CUDA/CPU op; per
    docs/adr/0003 detection *evaluation* is host-side here — dets come
    back from the fixed-shape multiclass_nms (ops/detection.py) and the
    PR/AP bookkeeping is plain numpy.

    update() takes per-batch ``(dets [N, K, 6] rows (label, score, x1,
    y1, x2, y2) padded with label -1, counts [N], gt_box [N, B, 4] xyxy
    zero-padded, gt_label [N, B], difficult [N, B] or None)``.
    """

    def __init__(self, class_num, overlap_threshold=0.5,
                 evaluate_difficult=False, ap_version="integral",
                 name="mAP"):
        super().__init__()
        if ap_version not in ("integral", "11point"):
            raise ValueError(f"ap_version {ap_version!r} not in "
                             "('integral', '11point')")
        self.class_num = int(class_num)
        self.thresh = float(overlap_threshold)
        self.eval_difficult = bool(evaluate_difficult)
        self.ap_version = ap_version
        self._name = name
        self.reset()

    def reset(self):
        # per class: list of (score, is_tp); gt count excl. difficult
        self._scores = [[] for _ in range(self.class_num)]
        self._npos = np.zeros(self.class_num, np.int64)

    @staticmethod
    def _iou(box, gts):
        ix1 = np.maximum(box[0], gts[:, 0])
        iy1 = np.maximum(box[1], gts[:, 1])
        ix2 = np.minimum(box[2], gts[:, 2])
        iy2 = np.minimum(box[3], gts[:, 3])
        iw = np.maximum(ix2 - ix1, 0)
        ih = np.maximum(iy2 - iy1, 0)
        inter = iw * ih
        a1 = (box[2] - box[0]) * (box[3] - box[1])
        a2 = (gts[:, 2] - gts[:, 0]) * (gts[:, 3] - gts[:, 1])
        return inter / np.maximum(a1 + a2 - inter, 1e-10)

    def update(self, dets, counts, gt_box, gt_label, difficult=None):
        dets, counts = _np(dets), _np(counts).astype(np.int64)
        gt_box, gt_label = _np(gt_box), _np(gt_label).astype(np.int64)
        difficult = (np.zeros_like(gt_label) if difficult is None
                     else _np(difficult).astype(np.int64))
        for n in range(dets.shape[0]):
            valid_gt = (gt_box[n, :, 2] > gt_box[n, :, 0]) & \
                       (gt_box[n, :, 3] > gt_box[n, :, 1])
            g_box = gt_box[n][valid_gt]
            g_lab = gt_label[n][valid_gt]
            g_dif = difficult[n][valid_gt]
            for c in range(self.class_num):
                self._npos[c] += int(((g_lab == c) & ((g_dif == 0) |
                                      self.eval_difficult)).sum())
            d = dets[n, :counts[n]]
            d = d[d[:, 0] >= 0]
            order = np.argsort(-d[:, 1], kind="stable")
            matched = np.zeros(len(g_box), bool)
            for row in d[order]:
                c = int(row[0])
                if not (0 <= c < self.class_num):
                    continue
                cand = np.where(g_lab == c)[0]
                if cand.size == 0:
                    self._scores[c].append((row[1], 0))
                    continue
                ious = self._iou(row[2:6], g_box[cand])
                j = int(np.argmax(ious))
                gi = cand[j]
                if ious[j] >= self.thresh:
                    if g_dif[gi] and not self.eval_difficult:
                        continue            # difficult match: ignore det
                    if not matched[gi]:
                        matched[gi] = True
                        self._scores[c].append((row[1], 1))
                    else:
                        self._scores[c].append((row[1], 0))
                else:
                    self._scores[c].append((row[1], 0))

    def accumulate(self):
        aps = []
        for c in range(self.class_num):
            if self._npos[c] == 0:
                continue
            if not self._scores[c]:
                aps.append(0.0)
                continue
            rec = np.asarray(self._scores[c], np.float64)
            order = np.argsort(-rec[:, 0], kind="stable")
            tp = np.cumsum(rec[order, 1])
            fp = np.cumsum(1 - rec[order, 1])
            recall = tp / self._npos[c]
            precision = tp / np.maximum(tp + fp, 1e-10)
            if self.ap_version == "11point":
                ap = 0.0
                for t in np.linspace(0, 1, 11):
                    mask = recall >= t
                    ap += (precision[mask].max() if mask.any() else 0.0) / 11
            else:
                # integral AP: sum precision deltas at each new recall level
                mrec = np.concatenate([[0], recall])
                ap = float(np.sum((mrec[1:] - mrec[:-1]) * precision))
            aps.append(float(ap))
        return float(np.mean(aps)) if aps else 0.0

    def name(self):
        return self._name


# reference exports `paddle.metric.metrics` (the defining submodule)
import sys as _sys
metrics = _sys.modules[__name__]
