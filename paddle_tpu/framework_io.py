"""paddle.save / paddle.load: pickle-based checkpoint of state dicts.

Reference: python/paddle/framework/io.py:494 save / :688 load — pickle of
numpy-ified tensors with >4GB protocol handling. Here tensors are converted
to numpy; nested dicts/lists (layer state_dict, optimizer state_dict) are
traversed. For sharded/async checkpoints of distributed training, see
paddle_tpu.incubate.checkpoint (orbax-style).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__paddle_tensor__": True, "data": np.asarray(obj._data),
            "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_saveable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    if hasattr(obj, "dtype") and hasattr(obj, "shape") and not isinstance(
            obj, np.ndarray) and type(obj).__module__.startswith("jax"):
        return np.asarray(obj)
    return obj


def _from_saved(obj, return_tensor=True):
    if isinstance(obj, dict):
        if obj.get("__paddle_tensor__"):
            if return_tensor:
                t = Tensor(obj["data"], stop_gradient=obj.get("stop_gradient", True),
                           name=obj.get("name"))
                return t
            return obj["data"]
        return {k: _from_saved(v, return_tensor) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_saved(v, return_tensor) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        blob = pickle.load(f)
    return _from_saved(blob, return_tensor=not return_numpy)


# -- reference-format checkpoint interop --------------------------------------
#
# The reference's paddle.save writes a pickled dict of numpy arrays with two
# metadata conventions (python/paddle/framework/io.py:672 +
# fluid/io.py:1714):
#   - "StructuredToParameterName@@": structured-name -> internal param name
#   - "UnpackBigParamInfor@@": >2^30-element params split into "<k>@@.<i>"
#     slices for pickle protocol 2/3
# and paddle 2.1 sometimes stored VarBase entries as (name, ndarray) tuples
# (io.py:327). These readers/writers speak that format so reference zoo
# checkpoints load here (`pretrained="/path/x.pdparams"`) and trained
# paddle_tpu weights can be shipped back.

_NAME_TABLE_KEY = "StructuredToParameterName@@"
_UNPACK_KEY = "UnpackBigParamInfor@@"


def load_reference_state_dict(path):
    """Read a reference-format ``.pdparams`` pickle into a plain
    {structured_name: np.ndarray} dict."""
    with open(path, "rb") as f:
        blob = pickle.load(f, encoding="latin1")
    if not isinstance(blob, dict):
        raise ValueError(
            f"{path}: expected a pickled state_dict, got {type(blob)}")
    blob = dict(blob)
    blob.pop(_NAME_TABLE_KEY, None)
    # reassemble chunked big params
    unpack = blob.pop(_UNPACK_KEY, None)
    if unpack:
        for key, info in unpack.items():
            slices = [blob.pop(part) for part in info["slices"]]
            blob[key] = np.concatenate(slices).reshape(info["OriginShape"])
    out = {}
    for k, v in blob.items():
        if isinstance(v, tuple) and len(v) == 2 and isinstance(
                v[1], np.ndarray):
            v = v[1]  # paddle-2.1 (tensor.name, ndarray) form
        if not isinstance(v, np.ndarray):
            raise ValueError(f"{path}: entry {k!r} is {type(v)}, "
                             "not an ndarray")
        out[k] = v
    return out


def save_reference_state_dict(state_dict, path, protocol=4,
                              _max_elements=None):
    """Write a reference-format ``.pdparams`` (the exporter direction:
    paddle_tpu weights usable by the reference's paddle.load)."""
    save_dict = {}
    name_table = {}
    for k, v in state_dict.items():
        arr = np.asarray(v._data if isinstance(v, Tensor) else v)
        save_dict[k] = arr
        name_table[k] = getattr(v, "name", None) or k
    if 1 < protocol < 4:
        unpack = {}
        for k in list(save_dict):
            v = save_dict[k]
            max_el = _max_elements or int((2 ** 30 - 1) / v.dtype.itemsize)
            if v.size > max_el:
                import math
                unpack[k] = {"OriginShape": v.shape, "slices": []}
                flat = save_dict.pop(k).ravel()
                for i in range(int(math.ceil(v.size / max_el))):
                    part = f"{k}@@.{i}"
                    unpack[k]["slices"].append(part)
                    save_dict[part] = flat[i * max_el:(i + 1) * max_el]
        if unpack:
            save_dict[_UNPACK_KEY] = unpack
    save_dict[_NAME_TABLE_KEY] = name_table
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(save_dict, f, protocol=protocol)


def convert_reference_checkpoint(path, model, strict=True, renames=None):
    """Load a reference-format checkpoint into a paddle_tpu Layer.

    Vision-zoo structured names match this framework's layers one-to-one
    (both sides mirror the reference's module tree), so the default map is
    identity; ``renames`` patches exceptions ({ref_name: our_name}).
    Returns (missing, unexpected) name lists; with ``strict`` a mismatch
    or any shape conflict raises.
    """
    src = load_reference_state_dict(path)
    if renames:
        for old, new in renames.items():
            if old in src:
                src[new] = src.pop(old)
    tgt = model.state_dict()
    missing = [k for k in tgt if k not in src]
    unexpected = [k for k in src if k not in tgt]
    if strict and (missing or unexpected):
        raise ValueError(
            f"convert_reference_checkpoint: missing={missing[:5]}... "
            f"unexpected={unexpected[:5]}... (strict=True)")
    for k, arr in src.items():
        if k not in tgt:
            continue
        want = tuple(tgt[k].shape)
        if tuple(arr.shape) != want:
            raise ValueError(
                f"convert_reference_checkpoint: {k} shape {arr.shape} != "
                f"model {want}")
    model.set_state_dict({k: v for k, v in src.items() if k in tgt})
    return missing, unexpected
