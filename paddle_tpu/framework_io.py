"""paddle.save / paddle.load: pickle-based checkpoint of state dicts.

Reference: python/paddle/framework/io.py:494 save / :688 load — pickle of
numpy-ified tensors with >4GB protocol handling. Here tensors are converted
to numpy; nested dicts/lists (layer state_dict, optimizer state_dict) are
traversed. For sharded/async checkpoints of distributed training, see
paddle_tpu.incubate.checkpoint (orbax-style).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__paddle_tensor__": True, "data": np.asarray(obj._data),
            "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_saveable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    if hasattr(obj, "dtype") and hasattr(obj, "shape") and not isinstance(
            obj, np.ndarray) and type(obj).__module__.startswith("jax"):
        return np.asarray(obj)
    return obj


def _from_saved(obj, return_tensor=True):
    if isinstance(obj, dict):
        if obj.get("__paddle_tensor__"):
            if return_tensor:
                t = Tensor(obj["data"], stop_gradient=obj.get("stop_gradient", True),
                           name=obj.get("name"))
                return t
            return obj["data"]
        return {k: _from_saved(v, return_tensor) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_saved(v, return_tensor) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        blob = pickle.load(f)
    return _from_saved(blob, return_tensor=not return_numpy)
