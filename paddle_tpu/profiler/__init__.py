"""paddle.profiler: tracing and profiling.

TPU-native equivalent of the reference profiler stack
(reference: paddle/fluid/platform/profiler.cc:59 RecordEvent RAII,
device_tracer.cc CUPTI timeline, python/paddle/fluid/profiler.py:314
``profiler`` context, start_profiler :190 / stop_profiler :257, and the
newer paddle.profiler.Profiler API). Here the device timeline comes from
XLA's own tracing via ``jax.profiler`` (viewable in TensorBoard /
Perfetto), host annotations map to ``jax.profiler.TraceAnnotation``, and
the op-dispatch funnel emits one annotation per op while a profile is
active (the reference pushes RecordEvent in Tracer::TraceOp,
imperative/tracer.cc:137).

Usage::

    with paddle.profiler.Profiler(log_dir="/tmp/prof") as prof:
        for batch in loader:
            train_step(batch)
            prof.step()
    # then: tensorboard --logdir /tmp/prof  (or xprof)
"""
from __future__ import annotations

import contextlib
import os
import time
from typing import Optional

import jax

# gate consulted by the op-dispatch funnel; a module-level list so the
# check is one indexing op on the eager hot path
_ACTIVE = [False]


def is_profiling() -> bool:
    return _ACTIVE[0]


class RecordEvent:
    """Host-side named annotation (reference: platform/profiler.cc:59
    RecordEvent; python: paddle.profiler.RecordEvent). Usable as a context
    manager or begin()/end() pair; shows up on the trace timeline."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self.begin_ns: Optional[int] = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self.begin_ns = time.perf_counter_ns()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """reference: paddle.profiler.Profiler (new API) /
    fluid/profiler.py:314 ``profiler`` context. Captures an XLA trace into
    ``log_dir``; ``step()`` emits per-step markers
    (jax.profiler.StepTraceAnnotation) that TensorBoard's profile tab
    groups by training step."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 log_dir: str = "./profiler_log", timer_only: bool = False):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self._on_trace_ready = on_trace_ready
        self._running = False
        self._step_no = 0
        self._step_ann = None
        self._step_times = []
        self._last_step_t = None

    def start(self):
        if self._running:
            return
        if not self.timer_only:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
        _ACTIVE[0] = True
        self._running = True
        self._last_step_t = time.perf_counter()
        self._begin_step_annotation()

    def stop(self):
        if not self._running:
            return
        self._end_step_annotation()
        _ACTIVE[0] = False
        if not self.timer_only:
            jax.profiler.stop_trace()
        self._running = False
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)

    def _begin_step_annotation(self):
        if not self.timer_only:
            self._step_ann = jax.profiler.StepTraceAnnotation(
                "train", step_num=self._step_no)
            self._step_ann.__enter__()

    def _end_step_annotation(self):
        if self._step_ann is not None:
            self._step_ann.__exit__(None, None, None)
            self._step_ann = None

    def step(self, num_samples: Optional[int] = None):
        """Mark a training-step boundary (reference: Profiler.step)."""
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._end_step_annotation()
        self._step_no += 1
        if self._running:
            self._begin_step_annotation()

    def step_info(self, unit=None) -> str:
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        ts = np.asarray(self._step_times)
        return (f"steps={len(ts)} avg={ts.mean() * 1e3:.3f}ms "
                f"min={ts.min() * 1e3:.3f}ms max={ts.max() * 1e3:.3f}ms")

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """The device-op table lives in the captured trace (TensorBoard /
        xprof); here we print the host-side step timing summary."""
        print(self.step_info())

    def export(self, path=None, format=None):
        return self.log_dir

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


# -- fluid-style module functions (reference: fluid/profiler.py) -------------

_FLUID_PROF: Optional[Profiler] = None


def start_profiler(state: str = "All", tracer_option: str = "Default",
                   log_dir: str = "./profiler_log"):
    """reference: fluid/profiler.py:190."""
    global _FLUID_PROF
    if _FLUID_PROF is None:
        _FLUID_PROF = Profiler(log_dir=log_dir)
        _FLUID_PROF.start()


def stop_profiler(sorted_key=None, profile_path: Optional[str] = None):
    """reference: fluid/profiler.py:257."""
    global _FLUID_PROF
    if _FLUID_PROF is not None:
        _FLUID_PROF.stop()
        _FLUID_PROF = None


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key=None, profile_path=None,
             tracer_option: str = "Default", log_dir: str = "./profiler_log"):
    """reference: fluid/profiler.py:314 (context-manager form)."""
    start_profiler(state, tracer_option, log_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    """CUDA-era no-op kept for ported scripts (reference:
    fluid/profiler.py cuda_profiler)."""
    yield


def reset_profiler():
    pass
