"""paddle.io parity: Dataset / Sampler / DataLoader.

Reference: python/paddle/fluid/dataloader/ (dataset.py, batch_sampler.py,
dataloader_iter.py:100 single-proc / :251 multi-proc with shared-memory
LoDTensor transport) and fluid/reader.py:149 DataLoader.

TPU design: worker processes produce numpy batches over a multiprocessing
queue; a background prefetch thread moves batches to device ahead of the
consumer (the role of the reference's BufferedReader double-buffer
(operators/reader/buffered_reader.h:36) — host→HBM copies overlap compute).
"""
from __future__ import annotations

import bisect
import itertools
import math
import os
import queue as _queue
import threading
import traceback
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from ..core import generator as _gen


class Dataset:
    """reference: fluid/dataloader/dataset.py Dataset (map-style)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = list(itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    if sum(lengths) != n:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.permutation(n)
    out, off = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[off:off + ln].tolist()))
        off += ln
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """reference: fluid/dataloader/batch_sampler.py."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """reference: fluid/dataloader/batch_sampler.py DistributedBatchSampler —
    shards the index space across ranks (on TPU: across data-parallel mesh
    coordinates / processes)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_world_size, get_rank
            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - len(indices)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# -- collate ---------------------------------------------------------------

def default_collate_fn(batch):
    """reference: fluid/dataloader/collate.py default_collate_fn."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch, 0)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._data) for s in batch], 0)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    return list(batch)


def default_convert_fn(batch):
    return batch


class _WorkerInfo:
    def __init__(self, wid, num_workers, dataset, seed):
        self.id = wid
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = [None]


def get_worker_info():
    return _worker_info[0]


def _worker_loop(dataset, index_queue, out_queue, collate_fn, wid,
                 num_workers, seed, iterable_mode, shm_name=None):
    """Worker process body (reference: dataloader_iter.py _worker_loop).
    With ``shm_name`` the batch payload goes through the C++ shared-memory
    ring (csrc/shm_ring.cpp) and only (order, "SHM", (wid, nbytes)) rides
    the queue — the reference's mmap_allocator transport."""
    np.random.seed((seed + wid) & 0xFFFFFFFF)
    _worker_info[0] = _WorkerInfo(wid, num_workers, dataset, seed)
    ring = None
    if shm_name is not None:
        from ..core.shm_ring import ShmRing
        ring = ShmRing(shm_name, create=False)

    def send(order, batch):
        if ring is not None:
            try:
                n = ring.push_object(batch)
                out_queue.put((order, "SHM", (wid, n)))
                return
            except ValueError:
                pass  # payload larger than the ring: queue fallback
        out_queue.put((order, "OK", batch))

    try:
        if iterable_mode:
            it = iter(dataset)
            while True:
                msg = index_queue.get()
                if msg is None:
                    break
                order, batch_size = msg
                try:
                    batch = list(itertools.islice(it, batch_size))
                    if not batch:
                        out_queue.put((order, "END", None))
                        continue
                    send(order, collate_fn(batch))
                except Exception:
                    out_queue.put((order, "ERR", traceback.format_exc()))
        else:
            while True:
                msg = index_queue.get()
                if msg is None:
                    break
                order, indices = msg
                try:
                    batch = [dataset[i] for i in indices]
                    send(order, collate_fn(batch))
                except Exception:
                    out_queue.put((order, "ERR", traceback.format_exc()))
    except KeyboardInterrupt:
        pass


class DataLoader:
    """reference: fluid/reader.py:149 DataLoader (return_list=True mode)."""

    _iter_serial = 0  # distinguishes shm namespaces of concurrent iterators

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=120, worker_init_fn=None,
                 persistent_workers=False, shm_capacity=64 << 20):
        self.dataset = dataset
        self.num_workers = num_workers
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self.return_list = return_list
        self.use_shared_memory = use_shared_memory
        self.shm_capacity = int(shm_capacity)
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            self.batch_size = batch_size
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def __call__(self):
        return iter(self)

    def __iter__(self):
        if self.num_workers == 0:
            return self._single_process_iter()
        return self._multi_process_iter()

    def _to_tensors(self, batch):
        if isinstance(batch, (list, tuple)):
            return [Tensor(b) if isinstance(b, np.ndarray) else b for b in batch]
        if isinstance(batch, np.ndarray):
            return [Tensor(batch)]
        if isinstance(batch, dict):
            return {k: Tensor(v) if isinstance(v, np.ndarray) else v
                    for k, v in batch.items()}
        return batch

    def _single_process_iter(self):
        if self._iterable:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch or (self.drop_last and len(batch) < self.batch_size):
                    return
                yield self._to_tensors(self.collate_fn(batch))
        else:
            for indices in self.batch_sampler:
                batch = [self.dataset[i] for i in indices]
                yield self._to_tensors(self.collate_fn(batch))

    def _multi_process_iter(self):
        """Worker pool + in-order delivery + host prefetch
        (reference: dataloader_iter.py:251 _DataLoaderIterMultiProcess)."""
        import multiprocessing as mp
        ctx = mp.get_context("fork" if os.name == "posix" else "spawn")
        index_queues = []
        out_queue = ctx.Queue()
        workers = []
        # rings are PER-ITERATOR state: two live iterators of one loader
        # must not share (or unlink) each other's rings
        rings = {}
        DataLoader._iter_serial += 1
        serial = DataLoader._iter_serial
        use_shm = False
        if self.use_shared_memory and os.name == "posix":
            from ..core.shm_ring import ShmRing, available as _shm_ok
            if _shm_ok():
                use_shm = True
        seed = int(np.random.randint(0, 2 ** 31))
        for wid in range(self.num_workers):
            iq = ctx.Queue()
            shm_name = None
            if use_shm:
                shm_name = f"/pt_dl_{os.getpid()}_{serial}_{wid}"
                rings[wid] = ShmRing(shm_name, create=True,
                                     capacity=self.shm_capacity)
            w = ctx.Process(
                target=_worker_loop,
                args=(self.dataset, iq, out_queue, self.collate_fn, wid,
                      self.num_workers, seed, self._iterable, shm_name),
                daemon=True)
            w.start()
            index_queues.append(iq)
            workers.append(w)

        try:
            if self._iterable:
                yield from self._mp_iterable(index_queues, out_queue, rings)
            else:
                yield from self._mp_map(index_queues, out_queue, rings)
        finally:
            for iq in index_queues:
                try:
                    iq.put(None)
                except Exception:
                    pass
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()
            for r in rings.values():
                r.close()

    def _mp_map(self, index_queues, out_queue, rings):
        batches = list(self.batch_sampler)
        n = len(batches)
        inflight = 0
        next_send = 0
        next_recv = 0
        hold = {}
        max_inflight = self.num_workers * self.prefetch_factor
        while next_recv < n:
            while next_send < n and inflight < max_inflight:
                index_queues[next_send % self.num_workers].put(
                    (next_send, batches[next_send]))
                next_send += 1
                inflight += 1
            order, status, payload = out_queue.get(timeout=self.timeout)
            inflight -= 1
            if status == "ERR":
                raise RuntimeError(f"DataLoader worker failed:\n{payload}")
            if status == "SHM":
                wid, nbytes = payload
                payload = rings[wid].pop_object(nbytes)
            hold[order] = payload
            while next_recv in hold:
                yield self._to_tensors(hold.pop(next_recv))
                next_recv += 1

    def _mp_iterable(self, index_queues, out_queue, rings):
        # each worker consumes its own iterator copy; messages tagged by wid
        live = set(range(self.num_workers))
        for wid in live:
            index_queues[wid].put((wid, self.batch_size))
        while live:
            wid, status, payload = out_queue.get(timeout=self.timeout)
            if status == "END":
                live.discard(wid)
                continue
            if status == "ERR":
                raise RuntimeError(f"DataLoader worker failed:\n{payload}")
            if status == "SHM":
                rwid, nbytes = payload
                payload = rings[rwid].pop_object(nbytes)
            if wid in live:
                index_queues[wid].put((wid, self.batch_size))
            yield self._to_tensors(payload)
