"""paddle.text: NLP datasets (reference: python/paddle/text/__init__.py —
Imdb, Imikolov, Movielens, UCIHousing, Conll05st, WMT14, WMT16 over
paddle.io.Dataset).

Offline-first: every dataset accepts ``data_file=`` pointing at the
original archive; ``download=True`` goes through paddle_tpu.utils.download
(clear error when the environment has no egress).
"""
from .datasets import (Imdb, Imikolov, Movielens, UCIHousing,  # noqa: F401
                       Conll05st, WMT14, WMT16)

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05st",
           "WMT14", "WMT16"]
