"""Text dataset implementations.

Reference: python/paddle/text/datasets/{imdb,imikolov,movielens,
uci_housing,conll05,wmt14,wmt16}.py. Formats and output tuples follow the
reference; parsing is reimplemented against the documented file layouts.
"""
from __future__ import annotations

import collections
import gzip
import io
import os
import re
import tarfile
import zipfile

import numpy as np

from ..io import Dataset
from ..utils.download import _check_exists_and_download

IMDB_URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"
IMDB_MD5 = "7c2ac02c03563afcf9b574c7e56c153a"
IMIKOLOV_URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz"
IMIKOLOV_MD5 = "30177ea32e27c525793142b6bf2c8e2d"
UCI_URL = "http://paddlemodels.bj.bcebos.com/uci_housing/housing.data"
UCI_MD5 = "d4accdce7a25600298819f8e28e8d593"
ML_URL = "https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip"
ML_MD5 = "c4d9eecfca2ab87c1945afe126590906"
CONLL_TEST_URL = "https://dataset.bj.bcebos.com/conll05st%2Fconll05st-tests.tar.gz"
CONLL_TEST_MD5 = "387719152ae52d60422c016e92a742fc"
WMT14_URL = ("https://dataset.bj.bcebos.com/wmt_shrinked_data%2F"
             "wmt14.tgz")
WMT14_MD5 = "0791583d57d5beb693b9414c5b36798c"
WMT16_URL = "https://dataset.bj.bcebos.com/wmt16%2Fwmt16.tar.gz"
WMT16_MD5 = "0c38be43600334966403524a40dcd81e"


class UCIHousing(Dataset):
    """reference: text/datasets/uci_housing.py — 13 features + price;
    features min/max/mean normalized over the whole table, 80/20 split."""

    def __init__(self, data_file=None, mode="train", download=True):
        assert mode in ("train", "test")
        self.mode = mode
        self.data_file = _check_exists_and_download(
            data_file, UCI_URL, UCI_MD5, "uci_housing", download)
        self._load_data()

    def _load_data(self, feature_num=14, ratio=0.8):
        data = np.loadtxt(self.data_file).reshape(-1, feature_num)
        maxs = data.max(axis=0)
        mins = data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
        offset = int(data.shape[0] * ratio)
        self.data = (data[:offset] if self.mode == "train"
                     else data[offset:]).astype(np.float32)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """reference: text/datasets/imdb.py — aclImdb tar; word dict built from
    train pos+neg with frequency ``cutoff``; doc = int64 ids, label 0=pos,
    1=neg."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        assert mode in ("train", "test")
        self.mode = mode
        self.data_file = _check_exists_and_download(
            data_file, IMDB_URL, IMDB_MD5, "imdb", download)
        self.word_idx = self._build_work_dict(cutoff)
        self._load_anno()

    def _tokenize(self, pattern):
        data = []
        with tarfile.open(self.data_file) as tarf:
            for member in tarf.getmembers():
                if pattern.match(member.name):
                    f = tarf.extractfile(member)
                    text = f.read().decode("latin-1").lower()
                    data.append(text.translate(
                        str.maketrans("", "", "!\"#$%&'()*+,-./:;<=>?@"
                                      "[\\]^_`{|}~")).split())
        return data

    def _build_work_dict(self, cutoff):
        pat = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
        word_freq = collections.Counter()
        for doc in self._tokenize(pat):
            word_freq.update(doc)
        word_freq["<unk>"] = cutoff + 1
        items = [(w, c) for w, c in word_freq.items() if c > cutoff]
        items.sort(key=lambda x: (-x[1], x[0]))
        return {w: i for i, (w, _) in enumerate(items)}

    def _load_anno(self):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for lab, name in ((0, "pos"), (1, "neg")):
            pat = re.compile(
                rf"aclImdb/{self.mode}/{name}/.*\.txt$")
            for doc in self._tokenize(pat):
                self.docs.append(np.array(
                    [self.word_idx.get(w, unk) for w in doc], np.int64))
                self.labels.append(np.array([lab], np.int64))

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """reference: text/datasets/imikolov.py — PTB; NGRAM windows or SEQ
    with <s>/<e> markers; dict from train with freq > min_word_freq."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        assert data_type in ("NGRAM", "SEQ")
        assert mode in ("train", "test")
        self.data_type = data_type
        self.window_size = window_size
        self.mode = mode
        self.min_word_freq = min_word_freq
        self.data_file = _check_exists_and_download(
            data_file, IMIKOLOV_URL, IMIKOLOV_MD5, "imikolov", download)
        self.word_idx = self._build_work_dict(min_word_freq)
        self._load_anno()

    def _lines(self, split):
        path = f"./simple-examples/data/ptb.{split}.txt"
        with tarfile.open(self.data_file) as tarf:
            f = tarf.extractfile(path)
            for line in io.TextIOWrapper(f, encoding="utf-8"):
                yield line.strip().split()

    def _build_work_dict(self, cutoff):
        freq = collections.Counter()
        for words in self._lines("train"):
            freq.update(words)
        freq.pop("<unk>", None)
        items = [(w, c) for w, c in freq.items() if c > cutoff]
        items.sort(key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(items)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx["<unk>"]
        self.data = []
        split = "train" if self.mode == "train" else "test"
        for words in self._lines(split):
            if self.data_type == "NGRAM":
                assert self.window_size > 0
                ws = ["<s>"] + words + ["<e>"]
                ids = [self.word_idx.get(w, unk) for w in ws]
                for i in range(self.window_size, len(ids) + 1):
                    self.data.append(
                        tuple(ids[i - self.window_size:i]))
            else:
                ids = [self.word_idx.get(w, unk)
                       for w in ["<s>"] + words + ["<e>"]]
                self.data.append((ids[:-1], ids[1:]))

    def __getitem__(self, idx):
        return tuple(np.array(x, np.int64) for x in self.data[idx]) \
            if self.data_type == "SEQ" else \
            np.array(self.data[idx], np.int64)

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """reference: text/datasets/movielens.py — ml-1m; each sample =
    (user_id, gender, age, job, movie_id, title_ids, category_ids,
    rating)."""

    AGES = [1, 18, 25, 35, 45, 50, 56]

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        assert mode in ("train", "test")
        self.mode = mode
        self.data_file = _check_exists_and_download(
            data_file, ML_URL, ML_MD5, "movielens", download)
        self._load(test_ratio, rand_seed)

    def _read(self, z, name):
        base = [n for n in z.namelist() if n.endswith(name)][0]
        return io.TextIOWrapper(z.open(base), encoding="latin-1")

    def _load(self, test_ratio, rand_seed):
        categories, titles_words = {}, {}
        movies, users = {}, {}
        with zipfile.ZipFile(self.data_file) as z:
            for line in self._read(z, "movies.dat"):
                mid, title, cats = line.strip().split("::")
                for c in cats.split("|"):
                    categories.setdefault(c, len(categories))
                words = title.lower().split()
                for w in words:
                    titles_words.setdefault(w, len(titles_words))
                movies[int(mid)] = (
                    [titles_words[w] for w in words],
                    [categories[c] for c in cats.split("|")])
            for line in self._read(z, "users.dat"):
                uid, gender, age, job, _zip = line.strip().split("::")
                users[int(uid)] = (0 if gender == "M" else 1,
                                   self.AGES.index(int(age)), int(job))
            rng = np.random.RandomState(rand_seed)
            self.data = []
            for line in self._read(z, "ratings.dat"):
                uid, mid, rating, _ts = line.strip().split("::")
                uid, mid = int(uid), int(mid)
                if mid not in movies or uid not in users:
                    continue
                is_test = rng.rand() < test_ratio
                if (self.mode == "test") != is_test:
                    continue
                g, a, j = users[uid]
                title_ids, cat_ids = movies[mid]
                self.data.append((uid, g, a, j, mid, title_ids, cat_ids,
                                  float(rating)))

    def __getitem__(self, idx):
        u, g, a, j, m, t, c, r = self.data[idx]
        return (np.array([u]), np.array([g]), np.array([a]), np.array([j]),
                np.array([m]), np.array(t), np.array(c),
                np.array([r], np.float32))

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """reference: text/datasets/conll05.py — SRL test split; sample =
    (word_ids, ctx_n2/n1/0/p1/p2 ids, predicate ids, mark, label_ids).
    Simplified faithful form: (word_ids, predicate_id, label_ids) over the
    props column format (one token per line: ``word pred-label``)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, download=True):
        self.data_file = _check_exists_and_download(
            data_file, CONLL_TEST_URL, CONLL_TEST_MD5, "conll05st",
            download)
        self.word_dict = (self._load_dict(word_dict_file)
                          if word_dict_file else None)
        self.label_dict = (self._load_dict(target_dict_file)
                           if target_dict_file else None)
        self._load()

    @staticmethod
    def _load_dict(path):
        with open(path) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    def _load(self):
        """Parses a two-column (word, label) props file, sentence per
        blank-line block; builds dicts on the fly when none supplied."""
        sentences = []
        words, labels = [], []
        opener = gzip.open if self.data_file.endswith(".gz") else open
        if tarfile.is_tarfile(self.data_file):
            with tarfile.open(self.data_file) as t:
                member = [m for m in t.getmembers()
                          if m.name.endswith(".props")
                          or m.name.endswith(".txt")][0]
                lines = t.extractfile(member).read().decode().splitlines()
        else:
            with opener(self.data_file, "rt") as f:
                lines = f.read().splitlines()
        for line in lines:
            parts = line.split()
            if not parts:
                if words:
                    sentences.append((words, labels))
                    words, labels = [], []
                continue
            words.append(parts[0])
            labels.append(parts[-1])
        if words:
            sentences.append((words, labels))
        if self.word_dict is None:
            vocab = sorted({w for ws, _ in sentences for w in ws})
            self.word_dict = {w: i for i, w in enumerate(vocab)}
        if self.label_dict is None:
            labs = sorted({l for _, ls in sentences for l in ls})
            self.label_dict = {l: i for i, l in enumerate(labs)}
        self.data = []
        for ws, ls in sentences:
            wid = np.array([self.word_dict.get(w, 0) for w in ws], np.int64)
            pred = int(np.argmax([l != "-" and l != "O" for l in ls])) \
                if ls else 0
            lid = np.array([self.label_dict.get(l, 0) for l in ls], np.int64)
            self.data.append((wid, np.int64(pred), lid))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class _WMTBase(Dataset):
    """Shared parallel-corpus machinery for WMT14/WMT16: src/trg token-id
    sequences with <s>/<e>/<unk> conventions (reference: wmt14.py BOS=0,
    EOS=1, UNK=2)."""

    BOS, EOS, UNK = 0, 1, 2

    def _parse_pairs(self, src_lines, trg_lines, src_dict, trg_dict):
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for s, t in zip(src_lines, trg_lines):
            s_ids = [src_dict.get(w, self.UNK) for w in s.split()]
            t_ids = [trg_dict.get(w, self.UNK) for w in t.split()]
            self.src_ids.append(np.array(s_ids, np.int64))
            self.trg_ids.append(np.array([self.BOS] + t_ids, np.int64))
            self.trg_ids_next.append(np.array(t_ids + [self.EOS], np.int64))

    def __getitem__(self, idx):
        return (self.src_ids[idx], self.trg_ids[idx],
                self.trg_ids_next[idx])

    def __len__(self):
        return len(self.src_ids)

    @staticmethod
    def _dict_from_lines(lines, size):
        d = {"<s>": 0, "<e>": 1, "<unk>": 2}
        for w in lines:
            w = w.strip()
            if w and w not in d and len(d) < size:
                d[w] = len(d)
        return d


class WMT14(_WMTBase):
    """reference: text/datasets/wmt14.py (shrunk en→fr corpus)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True):
        assert mode in ("train", "test", "gen")
        self.mode = mode
        self.data_file = _check_exists_and_download(
            data_file, WMT14_URL, WMT14_MD5, "wmt14", download)
        with tarfile.open(self.data_file) as t:
            names = t.getnames()

            def read(pred):
                ms = [n for n in names if pred(n)]
                out = []
                for m in ms:
                    out += t.extractfile(m).read().decode(
                        "utf-8", "ignore").splitlines()
                return out
            src_dict = self._dict_from_lines(
                read(lambda n: "src.dict" in n), dict_size)
            trg_dict = self._dict_from_lines(
                read(lambda n: "trg.dict" in n), dict_size)
            split = {"train": "train/", "test": "test/",
                     "gen": "gen/"}[mode]
            pairs = [n for n in names
                     if split in n and not n.endswith("/")]
            src_lines, trg_lines = [], []
            for n in sorted(pairs):
                body = t.extractfile(n).read().decode(
                    "utf-8", "ignore").splitlines()
                for line in body:
                    if "\t" in line:
                        s, tr = line.split("\t")[:2]
                        src_lines.append(s)
                        trg_lines.append(tr)
        self.src_dict, self.trg_dict = src_dict, trg_dict
        self._parse_pairs(src_lines, trg_lines, src_dict, trg_dict)


class WMT16(_WMTBase):
    """reference: text/datasets/wmt16.py (en↔de, separate dict files)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        assert mode in ("train", "test", "val")
        self.mode = mode
        self.lang = lang
        self.data_file = _check_exists_and_download(
            data_file, WMT16_URL, WMT16_MD5, "wmt16", download)
        trg_lang = "de" if lang == "en" else "en"
        with tarfile.open(self.data_file) as t:
            names = t.getnames()

            def read_one(frag):
                ms = [n for n in names if frag in n]
                if not ms:
                    return []
                return t.extractfile(ms[0]).read().decode(
                    "utf-8", "ignore").splitlines()
            src_dict = self._dict_from_lines(
                read_one(f"vocab_{lang}"), src_dict_size
                if src_dict_size > 0 else 10 ** 9)
            trg_dict = self._dict_from_lines(
                read_one(f"vocab_{trg_lang}"), trg_dict_size
                if trg_dict_size > 0 else 10 ** 9)
            pairs = read_one({"train": "train", "test": "test",
                              "val": "val"}[mode])
            src_lines, trg_lines = [], []
            for line in pairs:
                if "\t" in line:
                    s, tr = line.split("\t")[:2]
                    src_lines.append(s)
                    trg_lines.append(tr)
        self.src_dict, self.trg_dict = src_dict, trg_dict
        self._parse_pairs(src_lines, trg_lines, src_dict, trg_dict)
