"""Quantization (QAT + PTQ) — paddle.quantization / slim parity.

Reference: python/paddle/fluid/contrib/slim/quantization/ —
QuantizationTransformPass (fake-quant op insertion),
ImperativeQuantAware (imperative_qat.py, dygraph layer wrapping), PTQ
calibration, and the fake_quantize kernels
(operators/fake_quantize_op.cc: abs_max, channel_wise_abs_max,
moving_average_abs_max).

TPU design: fake-quant is expressed functionally with the straight-through
estimator — ``x + stop_gradient(quant(x) - x)`` — so autograd gives STE
for free and XLA fuses the whole simulate-quantize chain; no graph pass
is needed (layers are wrapped, the reference's dygraph path).
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import apply, apply_raw
from ..nn.layer_base import Layer
from ..nn import functional as F


def fake_quantize_abs_max(x, bit_length=8):
    """reference: fake_quantize_op.cc FakeQuantizeAbsMax — symmetric
    per-tensor quantize/dequantize with STE gradient. Returns (out, scale)."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def impl(a):
        scale = jnp.max(jnp.abs(a))
        s = jnp.where(scale > 0, scale, 1.0)
        q = jnp.round(a / s * qmax) / qmax * s
        # straight-through: value of q, gradient of a
        out = a + jax.lax.stop_gradient(q - a)
        return out, scale
    import jax
    return apply("fake_quantize_abs_max", impl, x)


def fake_channel_wise_quantize_abs_max(x, bit_length=8, quant_axis=0):
    """reference: fake_quantize_op.cc channel-wise variant (weights)."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def impl(a):
        import jax
        axes = tuple(i for i in range(a.ndim) if i != quant_axis)
        scale = jnp.max(jnp.abs(a), axis=axes, keepdims=True)
        s = jnp.where(scale > 0, scale, 1.0)
        q = jnp.round(a / s * qmax) / qmax * s
        out = a + jax.lax.stop_gradient(q - a)
        return out, scale.reshape(-1)
    return apply("fake_channel_wise_quantize_abs_max", impl, x)


class MovingAverageAbsMaxObserver:
    """reference: fake_quantize_op.cc FakeQuantizeMovingAverageAbsMax
    state (accum/state/scale buffers)."""

    def __init__(self, moving_rate=0.9):
        self._rate = moving_rate
        self.scale: Optional[float] = None

    def observe(self, x):
        raw = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        cur = float(jnp.max(jnp.abs(raw)))
        if self.scale is None:
            self.scale = cur
        else:
            self.scale = self._rate * self.scale + (1 - self._rate) * cur
        return self.scale


def quant_dequant_with_scale(x, scale, bit_length=8):
    """Simulated int quantize with a FIXED scale (PTQ inference form)."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def impl(a):
        import jax
        s = max(float(scale), 1e-8)
        q = jnp.clip(jnp.round(a / s * qmax), -qmax - 1, qmax) / qmax * s
        return a + jax.lax.stop_gradient(q - a)
    return apply("quant_dequant", impl, x)


class QuantedLinear(Layer):
    """Linear with fake-quantized weight + input (reference:
    slim/quantization/imperative/qat.py QuantizedLinear)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self._inner = layer
        self.weight = layer.weight
        self.bias = getattr(layer, "bias", None)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._observer = MovingAverageAbsMaxObserver(moving_rate)

    def forward(self, x):
        self._observer.observe(x)
        xq, _ = fake_quantize_abs_max(x, self._abits)
        wq, _ = fake_channel_wise_quantize_abs_max(self.weight, self._wbits,
                                                   quant_axis=1)
        return F.linear(xq, wq, self.bias)


class QuantedConv2D(Layer):
    """reference: imperative/qat.py QuantizedConv2D."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self._inner = layer
        self.weight = layer.weight
        self.bias = getattr(layer, "bias", None)
        self._cfg = {k: getattr(layer, k) for k in
                     ("_stride", "_padding", "_dilation", "_groups")
                     if hasattr(layer, k)}
        self._wbits = weight_bits
        self._abits = activation_bits
        self._observer = MovingAverageAbsMaxObserver(moving_rate)

    def forward(self, x):
        self._observer.observe(x)
        xq, _ = fake_quantize_abs_max(x, self._abits)
        wq, _ = fake_channel_wise_quantize_abs_max(self.weight, self._wbits,
                                                   quant_axis=0)
        return F.conv2d(xq, wq, self.bias,
                        stride=self._cfg.get("_stride", 1),
                        padding=self._cfg.get("_padding", 0),
                        dilation=self._cfg.get("_dilation", 1),
                        groups=self._cfg.get("_groups", 1))


class ImperativeQuantAware:
    """QAT driver (reference: slim/quantization/imperative/qat.py
    ImperativeQuantAware.quantize — swaps Linear/Conv2D sublayers for
    quantized wrappers in place)."""

    QUANT_MAP = None  # filled below

    def __init__(self, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 quantizable_layer_type=("Conv2D", "Linear"), **kw):
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self._types = set(quantizable_layer_type)

    def quantize(self, model: Layer):
        from ..nn import Linear, Conv2D
        for name, sub in list(model._sub_layers.items()):
            cls = type(sub).__name__
            # setattr (not a _sub_layers poke): Layer.__setattr__ keeps the
            # instance attribute and the registry in sync
            if cls == "Linear" and "Linear" in self._types:
                setattr(model, name, QuantedLinear(
                    sub, self._wbits, self._abits, self._rate))
            elif cls == "Conv2D" and "Conv2D" in self._types:
                setattr(model, name, QuantedConv2D(
                    sub, self._wbits, self._abits, self._rate))
            else:
                self.quantize(sub)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit
        jit.save(model, path, input_spec=input_spec)


class ImperativePTQ:
    """Post-training quantization (reference: slim/quantization/imperative/
    ptq.py): wrap, run calibration batches, then ``convert`` freezes the
    observed activation scales."""

    def __init__(self, quant_config=None):
        self._cfg = quant_config or {}

    def quantize(self, model: Layer):
        return ImperativeQuantAware().quantize(model)

    def convert(self, model: Layer):
        """Freeze observers: replace moving-average observation with the
        calibrated fixed scale."""
        for sub in model._sub_layers.values():
            if isinstance(sub, (QuantedLinear, QuantedConv2D)):
                scale = sub._observer.scale or 1.0

                def freeze(layer=sub, s=scale):
                    def fwd(x):
                        xq = quant_dequant_with_scale(x, s, layer._abits)
                        wq, _ = fake_channel_wise_quantize_abs_max(
                            layer.weight, layer._wbits,
                            quant_axis=1 if isinstance(layer, QuantedLinear)
                            else 0)
                        if isinstance(layer, QuantedLinear):
                            return F.linear(xq, wq, layer.bias)
                        return F.conv2d(
                            xq, wq, layer.bias,
                            stride=layer._cfg.get("_stride", 1),
                            padding=layer._cfg.get("_padding", 0),
                            dilation=layer._cfg.get("_dilation", 1),
                            groups=layer._cfg.get("_groups", 1))
                    return fwd
                sub.forward = freeze()
            else:
                self.convert(sub)
        return model
