"""Quantization (QAT + PTQ) — paddle.quantization / slim parity.

Reference: python/paddle/fluid/contrib/slim/quantization/ —
QuantizationTransformPass (fake-quant op insertion),
ImperativeQuantAware (imperative_qat.py, dygraph layer wrapping), PTQ
calibration, and the fake_quantize kernels
(operators/fake_quantize_op.cc: abs_max, channel_wise_abs_max,
moving_average_abs_max).

TPU design, two tiers:

- **Simulation (QAT)**: fake-quant is expressed functionally with the
  straight-through estimator — ``x + stop_gradient(quant(x) - x)`` — so
  autograd gives STE for free and XLA fuses the whole simulate-quantize
  chain; no graph pass is needed (layers are wrapped, the reference's
  dygraph path).
- **Execution (PTQ convert / save_quantized_model)**: ``convert`` swaps
  the wrappers for :class:`Int8Linear`/:class:`Int8Conv2D`, whose weights
  are REAL ``jnp.int8`` buffers with per-out-channel f32 scales — weight
  memory halves at rest and, when a calibrated activation scale exists,
  the Linear matmul runs as an int8×int8 ``dot_general`` with int32
  accumulation (the serving executable the reference's quantize-for-
  inference pass produces). The int8 buffers flow through ``jit.save`` →
  ``Predictor`` unchanged (StableHLO and the pickled .pdiparams both
  carry s8). See docs/quantization.md.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import apply, apply_raw
from ..nn.layer_base import Layer
from ..nn import functional as F


def fake_quantize_abs_max(x, bit_length=8):
    """reference: fake_quantize_op.cc FakeQuantizeAbsMax — symmetric
    per-tensor quantize/dequantize with STE gradient. Returns (out, scale)."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def impl(a):
        scale = jnp.max(jnp.abs(a))
        s = jnp.where(scale > 0, scale, 1.0)
        q = jnp.round(a / s * qmax) / qmax * s
        # straight-through: value of q, gradient of a
        out = a + jax.lax.stop_gradient(q - a)
        return out, scale
    return apply("fake_quantize_abs_max", impl, x)


def fake_channel_wise_quantize_abs_max(x, bit_length=8, quant_axis=0):
    """reference: fake_quantize_op.cc channel-wise variant (weights)."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def impl(a):
        axes = tuple(i for i in range(a.ndim) if i != quant_axis)
        scale = jnp.max(jnp.abs(a), axis=axes, keepdims=True)
        s = jnp.where(scale > 0, scale, 1.0)
        q = jnp.round(a / s * qmax) / qmax * s
        out = a + jax.lax.stop_gradient(q - a)
        return out, scale.reshape(-1)
    return apply("fake_channel_wise_quantize_abs_max", impl, x)


class MovingAverageAbsMaxObserver:
    """reference: fake_quantize_op.cc FakeQuantizeMovingAverageAbsMax
    state (accum/state/scale buffers).

    ``scale`` follows the repo's established EMA semantics (first batch
    initializes, then ``rate*scale + (1-rate)*cur`` — pinned by
    tests/test_op_tail_r5b.py); the reference's ``accum``/``state``
    buffers (``accum = rate*accum + cur``, ``state = rate*state + 1``)
    are maintained alongside purely so reference checkpoints round-trip.

    ``state_dict``/``set_state_dict`` round-trip the triple under BOTH the
    repo-style keys (``scale``/``accum``/``state``) and the reference's
    persistable-variable names (``OutScale``/``InAccum``/``InState``), the
    same dual-key convention the PR-5 GradScaler fix established for
    ``good_steps``/``incr_count`` — a checkpoint written by either side
    loads on the other.
    """

    #: (repo key, reference key) pairs, in emit order
    _KEYS = (("scale", "OutScale"), ("accum", "InAccum"),
             ("state", "InState"))

    def __init__(self, moving_rate=0.9):
        self._rate = moving_rate
        self.scale: Optional[float] = None
        self._accum = 0.0
        self._state = 0.0

    def observe(self, x):
        raw = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        # calibration is a cold path by contract: one concrete absmax per
        # calibration batch is the semantics (the reference kernel reads
        # cur_scale the same way)
        cur = float(jnp.max(jnp.abs(raw)))
        self._accum = self._rate * self._accum + cur
        self._state = self._rate * self._state + 1.0
        if self.scale is None:
            self.scale = cur
        else:
            self.scale = self._rate * self.scale + (1 - self._rate) * cur
        return self.scale

    def state_dict(self) -> Dict[str, np.ndarray]:
        vals = {"scale": 0.0 if self.scale is None else float(self.scale),
                "accum": float(self._accum), "state": float(self._state)}
        out = {}
        for repo_key, ref_key in self._KEYS:
            arr = np.asarray(vals[repo_key], np.float32)  # noqa: PTA002 -- state_dict serializes host python floats for checkpointing, not a per-step path
            out[repo_key] = arr
            out[ref_key] = arr
        return out

    def set_state_dict(self, state_dict):
        def pick(repo_key, ref_key):
            for k in (repo_key, ref_key):
                if k in state_dict:
                    v = state_dict[k]
                    return float(v.numpy() if isinstance(v, Tensor)  # noqa: PTA002 -- checkpoint load path: observer state must land as host floats once, not per step
                                 else np.asarray(v))  # noqa: PTA002 -- checkpoint load path, see above
            return None
        accum = pick("accum", "InAccum")
        state = pick("state", "InState")
        scale = pick("scale", "OutScale")
        if accum is not None:
            self._accum = accum
        if state is not None:
            self._state = state
        if scale is not None:
            self.scale = scale if state is None or state > 0 else None
        elif self._state > 0:
            self.scale = self._accum / self._state
        return self

    load_state_dict = set_state_dict


def quant_dequant_with_scale(x, scale, bit_length=8):
    """Simulated int quantize with a FIXED scale (PTQ inference form)."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def impl(a):
        s = max(float(scale), 1e-8)
        q = jnp.clip(jnp.round(a / s * qmax), -qmax - 1, qmax) / qmax * s
        return a + jax.lax.stop_gradient(q - a)
    return apply("quant_dequant", impl, x)


# -- real-int8 execution primitives ------------------------------------------

def quantize_weight_int8(w, quant_axis=1):
    """Per-channel symmetric int8 weight quantization (the EXECUTABLE form,
    not a simulation): returns ``(q, scale)`` with ``q`` int8 and ``scale``
    f32 of shape ``[channels]`` such that ``w ≈ q * scale`` broadcast over
    ``quant_axis``. ``scale = absmax / 127`` per channel."""
    raw = w._data if isinstance(w, Tensor) else jnp.asarray(w)
    axes = tuple(i for i in range(raw.ndim) if i != quant_axis)
    absmax = jnp.max(jnp.abs(raw), axis=axes, keepdims=True)
    scale = jnp.where(absmax > 0, absmax, 1.0).astype(jnp.float32) / 127.0
    q = jnp.clip(jnp.round(raw / scale), -127.0, 127.0).astype(jnp.int8)
    return q, scale.reshape(-1)


def _expand_scale(scale, ndim, quant_axis):
    shape = [1] * ndim
    shape[quant_axis] = -1
    return scale.reshape(shape)


class Int8Linear(Layer):
    """Linear whose weight is a real ``jnp.int8`` buffer with per-out-
    channel f32 scales (reference capability: the quantize-for-inference
    pass's dequantize-fused INT8 matmul).

    Two execution forms, picked by whether a calibrated activation scale
    exists:

    - ``act_scale`` set (PTQ convert): the input is quantized to int8 with
      the frozen scale and the matmul runs int8×int8 with int32
      accumulation (``lax.dot_general(..., preferred_element_type=int32)``)
      — the true serving kernel; XLA fuses quantize → dot → rescale.
    - ``act_scale`` None: weight-only int8 — the weight dequant fuses into
      the f32 matmul; activations stay f32.

    ``weight`` is exposed as a dequantized read-only view for API parity
    with ``nn.Linear`` (eval/export code that inspects ``.weight`` keeps
    working); the storage is int8.
    """

    def __init__(self, weight_q, w_scale, bias=None,
                 act_scale: Optional[float] = None):
        super().__init__()
        self.register_buffer("weight_q", weight_q if isinstance(
            weight_q, Tensor) else Tensor(jnp.asarray(weight_q, jnp.int8)))
        self.register_buffer("w_scale", w_scale if isinstance(
            w_scale, Tensor) else Tensor(jnp.asarray(w_scale, jnp.float32)))
        self.bias = bias
        self._act_scale = None if act_scale is None else float(act_scale)

    @classmethod
    def from_float(cls, weight, bias=None, act_scale=None):
        q, s = quantize_weight_int8(weight, quant_axis=1)
        return cls(Tensor(q), Tensor(s), bias=bias, act_scale=act_scale)

    @property
    def weight(self):
        """Dequantized f32 view (API parity; storage stays int8)."""
        return Tensor(self.weight_q._data.astype(jnp.float32)
                      * self.w_scale._data[None, :])

    def forward(self, x):
        act_scale = self._act_scale

        def impl(a, q, s, *rest):
            if act_scale is not None:
                sa = max(act_scale, 1e-8) / 127.0
                aq = jnp.clip(jnp.round(a / sa), -127.0, 127.0
                              ).astype(jnp.int8)
                acc = jax.lax.dot_general(
                    aq, q, (((a.ndim - 1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                out = acc.astype(jnp.float32) * (sa * s)
            else:
                out = (a @ q.astype(jnp.float32)) * s
            if rest:
                out = out + rest[0]
            return out
        args = (x, self.weight_q, self.w_scale)
        if self.bias is not None:
            args = args + (self.bias,)
        return apply("int8_linear", impl, *args)


class Int8Conv2D(Layer):
    """Conv2D with weight-only int8 storage: the weight lives as an int8
    buffer + per-out-channel scales and dequantizes into the f32
    convolution (XLA fuses the convert+scale into the conv). Activation
    int8 convolution is out of scope — conv serving traffic here is
    memory-bound on weights, which is what halving storage addresses."""

    def __init__(self, weight_q, w_scale, bias=None, stride=1, padding=0,
                 dilation=1, groups=1):
        super().__init__()
        self.register_buffer("weight_q", weight_q if isinstance(
            weight_q, Tensor) else Tensor(jnp.asarray(weight_q, jnp.int8)))
        self.register_buffer("w_scale", w_scale if isinstance(
            w_scale, Tensor) else Tensor(jnp.asarray(w_scale, jnp.float32)))
        self.bias = bias
        self._cfg = {"_stride": stride, "_padding": padding,
                     "_dilation": dilation, "_groups": groups}

    @classmethod
    def from_float(cls, weight, bias=None, **cfg):
        q, s = quantize_weight_int8(weight, quant_axis=0)
        return cls(Tensor(q), Tensor(s), bias=bias,
                   stride=cfg.get("_stride", 1),
                   padding=cfg.get("_padding", 0),
                   dilation=cfg.get("_dilation", 1),
                   groups=cfg.get("_groups", 1))

    @property
    def weight(self):
        return Tensor(self.weight_q._data.astype(jnp.float32)
                      * _expand_scale(self.w_scale._data,
                                      self.weight_q._data.ndim, 0))

    def forward(self, x):
        nd = self.weight_q._data.ndim

        def deq(q, s):
            return q.astype(jnp.float32) * _expand_scale(s, nd, 0)
        w = apply_raw("int8_dequant_weight", deq,
                      self.weight_q, self.w_scale)
        return F.conv2d(x, w, self.bias,
                        stride=self._cfg.get("_stride", 1),
                        padding=self._cfg.get("_padding", 0),
                        dilation=self._cfg.get("_dilation", 1),
                        groups=self._cfg.get("_groups", 1))


class _ObserverStateMixin:
    """Observer state joins the wrapper layer's state (the accum/state/
    scale triple is what makes a calibrated checkpoint reloadable — scale
    alone loses the running average)."""

    def state_dict(self, *args, **kwargs):
        dest = super().state_dict(*args, **kwargs)
        prefix = kwargs.get("structured_name_prefix", "")
        for k, v in self._observer.state_dict().items():
            dest[f"{prefix}_observer.{k}"] = v
        return dest

    def set_state_dict(self, state_dict, *args, **kwargs):
        obs = {k.split("_observer.", 1)[1]: v
               for k, v in state_dict.items() if "_observer." in k}
        if obs:
            self._observer.set_state_dict(obs)
        rest = {k: v for k, v in state_dict.items()
                if "_observer." not in k}
        return super().set_state_dict(rest, *args, **kwargs)


class QuantedLinear(_ObserverStateMixin, Layer):
    """Linear with fake-quantized weight + input (reference:
    slim/quantization/imperative/qat.py QuantizedLinear)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self._inner = layer
        self.weight = layer.weight
        self.bias = getattr(layer, "bias", None)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._observer = MovingAverageAbsMaxObserver(moving_rate)

    def forward(self, x):
        self._observer.observe(x)
        xq, _ = fake_quantize_abs_max(x, self._abits)
        wq, _ = fake_channel_wise_quantize_abs_max(self.weight, self._wbits,
                                                   quant_axis=1)
        return F.linear(xq, wq, self.bias)


class QuantedConv2D(_ObserverStateMixin, Layer):
    """reference: imperative/qat.py QuantizedConv2D."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self._inner = layer
        self.weight = layer.weight
        self.bias = getattr(layer, "bias", None)
        self._cfg = {k: getattr(layer, k) for k in
                     ("_stride", "_padding", "_dilation", "_groups")
                     if hasattr(layer, k)}
        self._wbits = weight_bits
        self._abits = activation_bits
        self._observer = MovingAverageAbsMaxObserver(moving_rate)

    def forward(self, x):
        self._observer.observe(x)
        xq, _ = fake_quantize_abs_max(x, self._abits)
        wq, _ = fake_channel_wise_quantize_abs_max(self.weight, self._wbits,
                                                   quant_axis=0)
        return F.conv2d(xq, wq, self.bias,
                        stride=self._cfg.get("_stride", 1),
                        padding=self._cfg.get("_padding", 0),
                        dilation=self._cfg.get("_dilation", 1),
                        groups=self._cfg.get("_groups", 1))


class ImperativeQuantAware:
    """QAT driver (reference: slim/quantization/imperative/qat.py
    ImperativeQuantAware.quantize — swaps Linear/Conv2D sublayers for
    quantized wrappers in place)."""

    QUANT_MAP = None  # filled below

    def __init__(self, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 quantizable_layer_type=("Conv2D", "Linear"), **kw):
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self._types = set(quantizable_layer_type)

    def quantize(self, model: Layer):
        from ..nn import Linear, Conv2D
        for name, sub in list(model._sub_layers.items()):
            cls = type(sub).__name__
            # setattr (not a _sub_layers poke): Layer.__setattr__ keeps the
            # instance attribute and the registry in sync
            if cls == "Linear" and "Linear" in self._types:
                setattr(model, name, QuantedLinear(
                    sub, self._wbits, self._abits, self._rate))
            elif cls == "Conv2D" and "Conv2D" in self._types:
                setattr(model, name, QuantedConv2D(
                    sub, self._wbits, self._abits, self._rate))
            else:
                self.quantize(sub)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        """reference: imperative_qat.py save_quantized_model — the export
        carries REAL int8 weights: the trained wrappers are converted to
        Int8Linear/Int8Conv2D (observer scales become frozen activation
        scales) and the resulting program — s8 buffers, dequant fused into
        the matmuls — is what jit.save exports and Predictor executes."""
        from .. import jit
        ImperativePTQ().convert(model)
        jit.save(model, path, input_spec=input_spec)


class ImperativePTQ:
    """Post-training quantization (reference: slim/quantization/imperative/
    ptq.py): wrap, run calibration batches, then ``convert`` swaps the
    wrappers for real-int8 execution layers."""

    def __init__(self, quant_config=None):
        self._cfg = quant_config or {}

    def quantize(self, model: Layer):
        return ImperativeQuantAware().quantize(model)

    def convert(self, model: Layer):
        """Freeze calibration into EXECUTABLE int8 layers: each
        QuantedLinear becomes an :class:`Int8Linear` (int8 weight buffer +
        per-channel scales + the observer's activation scale driving an
        int8×int8 matmul); each QuantedConv2D becomes an
        :class:`Int8Conv2D` (weight-only int8). Not a simulation — the
        f32 master weights are dropped and weight memory halves."""
        for name, sub in list(model._sub_layers.items()):
            if isinstance(sub, QuantedLinear):
                setattr(model, name, Int8Linear.from_float(
                    sub.weight, bias=sub.bias,
                    act_scale=sub._observer.scale))
            elif isinstance(sub, QuantedConv2D):
                setattr(model, name, Int8Conv2D.from_float(
                    sub.weight, bias=sub.bias, **sub._cfg))
            else:
                self.convert(sub)
        return model
