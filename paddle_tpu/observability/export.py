"""Chrome ``trace_event`` export for recorded spans.

Produces the JSON object format (``{"traceEvents": [...]}``) that both
``chrome://tracing`` and Perfetto load directly: one ``ph: "X"`` complete
event per span (microsecond timestamps) plus ``M`` metadata events naming
the process and threads. ``tools/trace_export.py`` is the CLI wrapper.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

from . import tracer as _tracer


def to_trace_events(spans: List[Dict], pid: int = 0,
                    process_name: str = "paddle_tpu") -> List[Dict]:
    """Convert span records (tracer ring schema) to trace_event dicts."""
    events: List[Dict] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
        "args": {"name": process_name},
    }]
    seen_tids = {}
    for s in spans:
        tid = s.get("tid", 0)
        if tid not in seen_tids:
            seen_tids[tid] = s.get("thread", "") or f"tid-{tid}"
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": seen_tids[tid]},
            })
        ev = {
            "ph": "X",
            "name": s["name"],
            "pid": pid,
            "tid": tid,
            "ts": s["ts_ns"] / 1e3,      # trace_event wants microseconds
            "dur": s["dur_ns"] / 1e3,
        }
        args = dict(s.get("attrs") or {})
        if s.get("depth"):
            args["depth"] = s["depth"]
        if args:
            ev["args"] = args
        events.append(ev)
    return events


def export_chrome_trace(path: str, spans: Optional[List[Dict]] = None,
                        tracer: Optional["_tracer.SpanTracer"] = None,
                        drain: bool = False) -> int:
    """Write spans as Chrome trace JSON; returns the number of span events.

    Defaults to a non-destructive snapshot of the default tracer; pass
    ``drain=True`` to also clear the ring (periodic export loops)."""
    t = tracer if tracer is not None else _tracer.default_tracer()
    if spans is None:
        spans = t.drain() if drain else t.spans()
    events = to_trace_events(spans, pid=t.pid)
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "clock": "perf_counter_ns",
            "clock_origin_ns": t.clock_origin_ns,
            "wall_origin_s": t.wall_origin_s,
            "dropped_spans": t.dropped,
        },
    }
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(spans)


def load_chrome_trace(path: str) -> Dict:
    """Load an exported trace (round-trip helper used by tests/tools)."""
    with open(path) as f:
        return json.load(f)
