"""Prometheus text exposition (format 0.0.4) over a ``StatRegistry``.

Maps the registry's dotted namespace onto Prometheus conventions:

* scalar stats created by ``add()`` -> ``counter`` with a ``_total``
  suffix; stats created by ``set()`` -> ``gauge``
* histograms -> ``summary`` families: ``{quantile="0.5|0.95|0.99"}``
  series plus ``_sum`` and ``_count``
* labeled gauges (``set_labeled``) -> one sample per label set, with
  label-value escaping per the exposition spec

Metric names are sanitized to ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (dots become
underscores) and namespaced ``paddle_tpu_``. Everything renders from one
``snapshot()`` so a scrape never mixes two points in time.

CONTENT_TYPE is what ``/metricsz`` must serve.
"""
from __future__ import annotations

import math
import re
from typing import Dict, List, Optional

from ..core import monitor as _monitor

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))


def sanitize_metric_name(name: str, namespace: str = "paddle_tpu") -> str:
    """Dotted stat name -> legal Prometheus metric name."""
    out = _NAME_OK.sub("_", name)
    if namespace:
        out = f"{namespace}_{out}"
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def escape_label_value(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def format_value(v) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if isinstance(v, int) or f.is_integer():
        return str(int(f))
    return repr(f)


def _labels_str(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{_NAME_OK.sub("_", k)}="{escape_label_value(v)}"'
                     for k, v in labels)
    return "{" + inner + "}"


def render_prometheus(registry: Optional["_monitor.StatRegistry"] = None,
                      namespace: str = "paddle_tpu") -> str:
    """Render one registry as Prometheus text exposition."""
    reg = registry if registry is not None else _monitor.default_registry()
    snap = reg.snapshot()
    lines: List[str] = []
    emitted: set = set()  # family names already given HELP/TYPE

    def family(metric: str, kind: str, help_text: str) -> bool:
        """Emit HELP/TYPE once per family; False if the sanitized name
        collided with an already-emitted family (sample is skipped — two
        families with one name would be invalid exposition)."""
        if metric in emitted:
            return False
        emitted.add(metric)
        lines.append(f"# HELP {metric} {escape_help(help_text)}")
        lines.append(f"# TYPE {metric} {kind}")
        return True

    stats: Dict = snap["stats"]
    kinds: Dict = snap["kinds"]
    for name in sorted(stats):
        kind = kinds.get(name, "gauge")
        metric = sanitize_metric_name(name, namespace)
        if kind == "counter" and not metric.endswith("_total"):
            metric += "_total"
        if family(metric, kind, f"paddle_tpu stat `{name}`"):
            lines.append(f"{metric} {format_value(stats[name])}")

    for name in sorted(snap["histograms"]):
        s = snap["histograms"][name]
        metric = sanitize_metric_name(name, namespace)
        if not family(metric, "summary", f"paddle_tpu histogram `{name}`"):
            continue
        for q, key in _QUANTILES:
            lines.append(f'{metric}{{quantile="{q}"}} '
                         f"{format_value(s[key])}")
        lines.append(f"{metric}_sum {format_value(s['sum'])}")
        lines.append(f"{metric}_count {format_value(s['count'])}")

    for name in sorted(snap["labeled"]):
        metric = sanitize_metric_name(name, namespace)
        if not family(metric, "gauge", f"paddle_tpu labeled gauge `{name}`"):
            continue
        for labels, value in sorted(snap["labeled"][name].items()):
            lines.append(f"{metric}{_labels_str(labels)} "
                         f"{format_value(value)}")

    return "\n".join(lines) + "\n" if lines else ""
