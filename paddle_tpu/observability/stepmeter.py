"""Per-step MFU / FLOPs accounting (``train.mfu`` and friends).

Combines two measurements:

* **FLOPs per step** from XLA cost analysis — the same
  ``jit(...).lower(...).compile().cost_analysis()`` API ``paddle.flops``
  uses, divided by 2 to match the MAC-as-one-FLOP convention shared by
  ``paddle.flops`` and bench.py's analytic constants (ResNet-50 fwd @224
  = 4.09 GFLOPs/img under that convention; XLA reports ~8.2e9 raw).
* **Step wall time** measured by the caller around a *synchronizing* step
  (the hapi train loop's loss fetch forces the sync, so wall time there is
  real device+host time, not async-dispatch time — the LazyTensor
  distinction PAPERS.md stresses).

``StepMeter.step(wall_s)`` publishes ``<prefix>.mfu``,
``<prefix>.flops_per_step`` and a ``<prefix>.step_ms`` histogram to a
StatRegistry, replacing hand-computed bench numbers with live stats.
"""
from __future__ import annotations

import os
from typing import Optional

from ..core import monitor as _monitor


def default_peak_flops() -> float:
    """Peak FLOP/s of the local accelerator, bench.py's convention:
    197 TFLOP/s for the TPU bench target, 1 TFLOP/s as the CPU-proxy
    normalizer. Override with ``PADDLE_TPU_PEAK_FLOPS`` (FLOP/s)."""
    env = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    try:
        import jax
        platform = jax.devices()[0].platform
    except Exception:
        platform = "cpu"
    if platform == "tpu":
        return 197.0e12
    if platform == "gpu":
        return 394.0e12
    return 1.0e12


def compiled_flops(fn, *args, jit_kwargs: Optional[dict] = None,
                   mac_convention: bool = True, **kwargs) -> Optional[float]:
    """FLOPs of one execution of ``fn(*args, **kwargs)`` per XLA cost
    analysis (compiles without executing). Returns None when the backend
    reports no cost model. ``mac_convention`` halves XLA's raw count to
    match ``paddle.flops`` / bench.py accounting."""
    import jax
    try:
        compiled = jax.jit(fn, **(jit_kwargs or {})).lower(
            *args, **kwargs).compile()
        costs = compiled.cost_analysis()
        if isinstance(costs, (list, tuple)):  # older jax returns [dict]
            costs = costs[0] if costs else {}
        flops = float(costs.get("flops", 0.0))
    except Exception:
        return None
    if flops <= 0.0:
        return None
    return flops / 2.0 if mac_convention else flops


class StepMeter:
    """Publishes live MFU from (flops per step, measured wall per step).

    ``flops_per_step`` is set once per compiled signature (cost analysis
    is a compile, not a per-step cost); ``step()`` is the per-step hot
    call — two registry writes and one histogram observe."""

    def __init__(self, peak_flops: Optional[float] = None,
                 registry: Optional["_monitor.StatRegistry"] = None,
                 prefix: str = "train"):
        self.peak_flops = (float(peak_flops) if peak_flops
                           else default_peak_flops())
        self.registry = (registry if registry is not None
                         else _monitor.default_registry())
        self.prefix = prefix
        self.flops_per_step: Optional[float] = None
        self.last_mfu: Optional[float] = None

    def set_flops_per_step(self, flops: Optional[float]):
        if flops:
            self.flops_per_step = float(flops)
            self.registry.set(f"{self.prefix}.flops_per_step",
                              self.flops_per_step)

    def measure_flops(self, fn, *args, jit_kwargs: Optional[dict] = None,
                      **kwargs) -> Optional[float]:
        """Cost-analyze ``fn`` and adopt the result as flops_per_step."""
        self.set_flops_per_step(compiled_flops(
            fn, *args, jit_kwargs=jit_kwargs, **kwargs))
        return self.flops_per_step

    def step(self, wall_s: float, flops: Optional[float] = None
             ) -> Optional[float]:
        """Record one step; returns the step's MFU (None if flops or wall
        are unknown). ``flops`` overrides the sticky per-signature value
        (e.g. a step that ran a different compiled program)."""
        reg = self.registry
        p = self.prefix
        reg.observe(f"{p}.step_ms", wall_s * 1e3)
        f = flops if flops is not None else self.flops_per_step
        if not f or wall_s <= 0.0:
            return None
        mfu = f / wall_s / self.peak_flops
        self.last_mfu = mfu
        reg.set(f"{p}.mfu", mfu)
        reg.observe(f"{p}.mfu_pct", mfu * 100.0)
        return mfu
