"""Crash flight recorder: last-N events + spans + stats, dumped on failure.

A bounded ring (GIL-atomic deque, same lock-free discipline as the span
tracer) continuously records cheap structured events — sentinel verdicts,
drain transitions, engine lifecycle. On a terminal event the ring is
dumped as JSONL so the post-mortem has the timeline that led to the exit:

* sentinel **halt** (exit 119) — ``paddle_tpu.sentinel.policy`` dumps
  before ``sys.exit``
* **unhandled exception** in guarded loops (hapi ``fit``, engine workers)
* **SIGTERM drain** — via ``install_signal_dump()`` on the existing
  ``ChainedSignalHandler`` chain, or the engines' drain path

Dump format (``flight_<ts>_<pid>.jsonl``): line 1 is a header
``{"schema": "paddle-tpu-flight/2", "reason": ...}``; then one line per
recorded event (``{"kind": ...}``), then the last spans
(``{"kind": "span", ...}``), and a final ``{"kind": "stats", ...}``
registry snapshot. Schema /2 adds ``process_index`` / ``process_count`` /
``cohort_generation`` to the header so a multi-host post-mortem can be
collated across per-host dumps and cohort re-formations
(docs/fault_tolerance.md, "Surviving host loss").

Dumping on crash paths is **opt-in** ("armed"): set ``PADDLE_TPU_FLIGHT=1``
(or call ``arm()``; enabling tracing also arms) so ordinary test failures
don't litter dump files. Recording into the ring is always on — it is two
dict allocs per event.
"""
from __future__ import annotations

import json
import os
import sys
import time
from collections import deque
from typing import Dict, List, Optional

from ..core import monitor as _monitor
from . import tracer as _tracer

SCHEMA = "paddle-tpu-flight/2"

DEFAULT_CAPACITY = int(os.environ.get("PADDLE_TPU_FLIGHT_CAPACITY", "512"))

#: how many of the newest spans a dump includes
DUMP_SPAN_LIMIT = 256


def _cohort_generation() -> int:
    """Cohort generation stamped by the elastic supervisor (0 outside it)."""
    try:
        return int(os.environ.get("PADDLE_TPU_COHORT_GEN", "0"))
    except ValueError:
        return 0


def _process_identity() -> Dict:
    """``process_index``/``process_count`` for the dump header, read from
    the PADDLE_* env contract (always set under the launcher) rather than
    asked of jax — a crash-path writer must never trigger a backend
    init/collective, least of all while a peer is already dead."""
    try:
        return {
            "process_index": int(os.environ.get("PADDLE_TRAINER_ID", "0")),
            "process_count": int(os.environ.get("PADDLE_TRAINERS_NUM", "1")),
        }
    except ValueError:
        return {"process_index": 0, "process_count": 1}


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._events = deque(maxlen=capacity)  # GIL-atomic append
        self.armed = False
        self.last_dump_path: Optional[str] = None

    def record(self, kind: str, fields: Optional[Dict] = None):
        ev = {"kind": kind, "wall_s": time.time()}
        if fields:
            ev.update(fields)
        self._events.append(ev)

    def events(self) -> List[Dict]:
        return list(self._events)

    def clear(self):
        self._events.clear()

    def dump(self, reason: str, directory: Optional[str] = None,
             registry: Optional["_monitor.StatRegistry"] = None,
             tracer: Optional["_tracer.SpanTracer"] = None) -> str:
        """Write the flight JSONL; returns the path. Never raises (a
        post-mortem writer must not mask the original failure) — on write
        error it returns the path it attempted."""
        directory = (directory
                     or os.environ.get("PADDLE_TPU_FLIGHT_DIR")
                     or os.getcwd())
        ts = time.strftime("%Y%m%d_%H%M%S")
        path = os.path.join(directory,
                            f"flight_{ts}_{os.getpid()}.jsonl")
        t = tracer if tracer is not None else _tracer.default_tracer()
        reg = registry if registry is not None else _monitor.default_registry()
        try:
            os.makedirs(directory, exist_ok=True)
            with open(path, "w") as f:
                header = {
                    "schema": SCHEMA,
                    "reason": reason,
                    "pid": os.getpid(),
                    "wall_s": time.time(),
                    "argv": list(sys.argv),
                    "cohort_generation": _cohort_generation(),
                }
                header.update(_process_identity())
                f.write(json.dumps(header, default=str) + "\n")
                for ev in list(self._events):
                    f.write(json.dumps(ev, default=str) + "\n")
                for s in t.spans()[-DUMP_SPAN_LIMIT:]:
                    rec = {"kind": "span"}
                    rec.update(s)
                    f.write(json.dumps(rec, default=str) + "\n")
                snap = reg.snapshot()
                f.write(json.dumps({"kind": "stats",
                                    "stats": snap["stats"],
                                    "histograms": snap["histograms"]},
                                   default=str) + "\n")
        except OSError as e:
            sys.stderr.write(f"[paddle_tpu.flight] dump to {path} "
                             f"failed: {e}\n")
        self.last_dump_path = path
        return path


_RECORDER = FlightRecorder()


def default_recorder() -> FlightRecorder:
    return _RECORDER


def record_event(kind: str, fields: Optional[Dict] = None):
    """Append one event to the default flight ring (always cheap/on)."""
    _RECORDER.record(kind, fields)


def arm():
    """Enable crash-path dumps (sentinel halt / unhandled exception /
    SIGTERM drain). Recording is always on; arming controls file output."""
    _RECORDER.armed = True


def disarm():
    _RECORDER.armed = False


def is_armed() -> bool:
    return _RECORDER.armed


def dump(reason: str, directory: Optional[str] = None) -> str:
    return _RECORDER.dump(reason, directory=directory)


def dump_if_armed(reason: str) -> Optional[str]:
    """Crash-path hook: dump only when armed, never raise."""
    if not _RECORDER.armed:
        return None
    return _RECORDER.dump(reason)


def install_signal_dump(signum: Optional[int] = None):
    """Chain a flight dump onto SIGTERM (preemption) via the shared
    ChainedSignalHandler — previously-installed handlers (engine drain,
    elastic supervisor) still run. Returns the handler (``uninstall()``
    to remove); None off the main thread."""
    import signal as _signal
    from ..distributed.elastic import ChainedSignalHandler

    sig = signum if signum is not None else _signal.SIGTERM

    def _on_signal(s, frame):
        record_event("signal", {"signum": s})
        dump_if_armed("signal_%d" % s)

    h = ChainedSignalHandler(_on_signal, signals=(sig,))
    h.install()
    return h if h.installed else None


if os.environ.get("PADDLE_TPU_FLIGHT", "").lower() in ("1", "true", "on"):
    arm()
