"""Structured span tracer: low-overhead host-side timeline spans.

Design constraints (ISSUE 8, cf. arxiv 2301.13062 — fusion/copy/transfer
pathologies are only findable with per-step cost *and timeline* data):

* **Disabled cost ~= one list index.** ``span()`` is called on every train
  step, every decode tick and every serving request, so the off path must
  allocate nothing: a module-level ``_ENABLED = [False]`` gate (mirroring
  ``profiler._ACTIVE``) short-circuits to one shared immutable no-op
  context manager. Hot paths must go through ``span()`` — constructing
  ``Span`` directly bypasses the gate (policed by PTA005's span-fastpath
  sub-check).
* **Lock-free recording.** Finished spans land in a bounded
  ``deque(maxlen=...)`` — CPython deque append/iteration are GIL-atomic,
  so worker threads, the train loop and a signal-triggered flight dump can
  share the ring without a lock (same discipline as the sentinel's halt
  path; see PTA006 notes in tools/analyze).
* **Timeline alignment.** When a ``paddle_tpu.profiler`` trace is active,
  each span also enters a ``jax.profiler.TraceAnnotation`` so host spans
  line up with XLA's device timeline in the same Perfetto view.

Timestamps are ``time.perf_counter_ns`` (monotonic); ``clock_origin_ns``
is recorded so exporters can map onto wall time.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .. import profiler as _profiler

#: module-level gate, mirroring ``profiler._ACTIVE``: a one-element list so
#: the hot-path check is a single LOAD + index with no attribute lookup on
#: a rebindable global.
_ENABLED = [False]

#: default ring capacity (finished spans retained). ~200 bytes/span.
DEFAULT_CAPACITY = int(os.environ.get("PADDLE_TPU_TRACE_CAPACITY", "8192"))

_tls = threading.local()


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


class _NoopSpan:
    """Shared do-nothing context manager returned while tracing is off.

    One module-level instance; ``__enter__``/``__exit__`` do no work, so an
    instrumented call site costs one function call + one index when
    tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set_attr(self, key, value):  # API parity with Span
        return self


NOOP_SPAN = _NoopSpan()


class Span:
    """One timed region. Create via ``span()`` / ``SpanTracer.span()`` —
    never directly in hot paths (the constructor runs even when tracing is
    disabled, defeating the fast path)."""

    __slots__ = ("name", "attrs", "t0_ns", "t1_ns", "tid", "thread_name",
                 "depth", "_tracer", "_ann")

    def __init__(self, tracer: "SpanTracer", name: str,
                 attrs: Optional[Dict] = None):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.t0_ns = 0
        self.t1_ns = 0
        self.tid = 0
        self.thread_name = ""
        self.depth = 0
        self._ann = None

    def set_attr(self, key, value):
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value
        return self

    def __enter__(self):
        t = threading.current_thread()
        self.tid = t.ident or 0
        self.thread_name = t.name
        stack = _stack()
        self.depth = len(stack)
        stack.append(self)
        if _profiler._ACTIVE[0]:
            try:
                import jax
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self.t0_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.t1_ns = time.perf_counter_ns()
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
            self._ann = None
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        else:  # unbalanced exit (e.g. generator abandoned mid-span)
            try:
                stack.remove(self)
            except ValueError:
                pass
        if exc_type is not None:
            self.set_attr("error", exc_type.__name__)
        self._tracer._record(self)
        return False


class SpanTracer:
    """Span factory + bounded ring of finished spans.

    The module-level default tracer (``enable()``/``span()``) is what all
    built-in instrumentation uses; standalone tracers exist for tests."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = capacity
        self._ring = deque(maxlen=capacity)  # GIL-atomic append
        self._dropped = 0
        self.pid = os.getpid()
        # perf_counter->wall mapping, refreshed on enable()
        self.clock_origin_ns = time.perf_counter_ns()
        self.wall_origin_s = time.time()

    # -- recording ----------------------------------------------------------
    def span_always(self, name: str, attrs: Optional[Dict] = None) -> Span:
        """Unconditionally-recording span (tests, cold paths). Hot paths
        must use the module-level ``span()`` — it is the only entry point
        with the zero-alloc disabled fast path (PTA005 polices this)."""
        return Span(self, name, attrs)

    def _record(self, s: Span):
        ring = self._ring
        if len(ring) == ring.maxlen:
            self._dropped += 1
        ring.append({
            "name": s.name,
            "ts_ns": s.t0_ns,
            "dur_ns": s.t1_ns - s.t0_ns,
            "tid": s.tid,
            "thread": s.thread_name,
            "depth": s.depth,
            "attrs": s.attrs,
        })

    # -- readout ------------------------------------------------------------
    def drain(self) -> List[Dict]:
        """Snapshot and clear the ring (export consumes spans once)."""
        out = []
        ring = self._ring
        while True:
            try:
                out.append(ring.popleft())
            except IndexError:
                return out

    def spans(self) -> List[Dict]:
        """Non-destructive snapshot of recorded spans, oldest first."""
        return list(self._ring)

    @property
    def dropped(self) -> int:
        return self._dropped

    def clear(self):
        self._ring.clear()
        self._dropped = 0


_TRACER = SpanTracer()


def default_tracer() -> SpanTracer:
    return _TRACER


def is_enabled() -> bool:
    return _ENABLED[0]


def enable(capacity: Optional[int] = None):
    """Turn span recording on (idempotent). ``capacity`` resizes the ring."""
    if capacity is not None and capacity != _TRACER.capacity:
        _TRACER.capacity = capacity
        _TRACER._ring = deque(_TRACER._ring, maxlen=capacity)
    _TRACER.clock_origin_ns = time.perf_counter_ns()
    _TRACER.wall_origin_s = time.time()
    _ENABLED[0] = True


def disable():
    _ENABLED[0] = False


def span(name: str, attrs: Optional[Dict] = None):
    """The instrumentation entry point: ``with span("train/step"): ...``.

    Returns the shared no-op when tracing is disabled — zero allocation on
    the hot path. Pass attributes as a dict (``span("x", {"k": v})``) only
    where the dict itself is cheap relative to the region timed."""
    if not _ENABLED[0]:
        return NOOP_SPAN
    return Span(_TRACER, name, attrs)


# re-exported by paddle_tpu.observability; env opt-in lives here so the
# import side effect is one getenv.
if os.environ.get("PADDLE_TPU_TRACE", "").lower() in ("1", "true", "on"):
    enable()
