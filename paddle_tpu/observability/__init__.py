"""paddle_tpu.observability — unified runtime telemetry.

One measurement substrate spanning training, serving and LLM decode
(docs/observability.md):

* :mod:`.tracer` — structured span tracer (``with span("train/step")``)
  with a zero-alloc disabled fast path; Chrome/Perfetto export via
  :mod:`.export` / ``tools/trace_export.py``.
* :mod:`.metrics` — Prometheus text exposition of a ``StatRegistry``
  (served at ``/metricsz`` by ``paddle_tpu.serving.http``).
* :mod:`.stepmeter` — per-step MFU/FLOPs accounting from XLA cost
  analysis + measured wall time (``train.mfu``, ``serving.llm.mfu``).
* :mod:`.flight` — crash flight recorder (last-N events/spans/stats as
  JSONL on sentinel halt, unhandled loop exceptions, SIGTERM drain).

``enable()`` turns the whole substrate on (span recording + armed flight
recorder); instrumented call sites cost ~one indexed load when disabled.
"""
from __future__ import annotations

from typing import Optional

from . import export, flight, metrics, stepmeter, tracer  # noqa: F401
from .export import export_chrome_trace, load_chrome_trace  # noqa: F401
from .flight import (FlightRecorder, default_recorder,  # noqa: F401
                     record_event)
from .metrics import render_prometheus  # noqa: F401
from .stepmeter import StepMeter, compiled_flops  # noqa: F401
from .tracer import (SpanTracer, default_tracer, is_enabled,  # noqa: F401
                     span)


def enable(capacity: Optional[int] = None):
    """Enable span recording and arm the flight recorder."""
    tracer.enable(capacity)
    flight.arm()


def disable():
    """Stop span recording and disarm crash-path dumps (the recorded ring
    and flight events are kept until ``tracer.default_tracer().clear()``)."""
    tracer.disable()
    flight.disarm()
