"""paddle.batch + paddle.reader decorators (reference:
python/paddle/batch.py, python/paddle/reader/decorator.py).

Pure-python generator combinators — no device interaction, so the
reference semantics carry over unchanged. ``xmap_readers``/
``multiprocess_reader`` are served by the DataLoader's worker pool
(io/__init__.py) rather than re-implementing a second process fabric;
thin thread-based equivalents are provided for API parity.
"""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["batch", "cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers", "ComposeNotAligned"]


class ComposeNotAligned(ValueError):
    """reference: decorator.py ComposeNotAligned — compose() inputs have
    different lengths with check_alignment=True."""


def batch(reader, batch_size, drop_last=False):
    """reference: batch.py:18 — group instances into lists."""
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


def cache(reader):
    """reference: decorator.py:51 — materialise once, replay from RAM.
    A partial first pass (reader raised mid-way) is discarded, not
    committed — a retry re-reads from scratch instead of replaying a
    duplicated prefix."""
    state = {}

    def cached():
        if "data" not in state:
            state["data"] = list(reader())   # commit only on full success
        yield from state["data"]
    return cached


def map_readers(func, *readers):
    """reference: decorator.py:91 — zip readers and map func over rows."""

    def reader():
        for vals in zip(*[r() for r in readers]):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    """reference: decorator.py:133 — windowed shuffle."""

    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return shuffled


def chain(*readers):
    """reference: decorator.py:182 — concatenate readers."""

    def chained():
        return itertools.chain(*[r() for r in readers])
    return chained


def compose(*readers, **kwargs):
    """reference: decorator.py:247 — zip readers into flat tuples."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    _SENTINEL = object()

    def composed():
        rs = [r() for r in readers]
        if check_alignment:
            # reference semantics: misaligned lengths RAISE, never
            # silently truncate
            for outputs in itertools.zip_longest(*rs,
                                                 fillvalue=_SENTINEL):
                if any(o is _SENTINEL for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            for outputs in zip(*rs):     # truncate at the shortest
                yield sum((make_tuple(o) for o in outputs), ())
    return composed


def buffered(reader, size):
    """reference: decorator.py:307 — background-thread prefetch."""

    class _End:
        pass

    def buffered_reader():
        q = _queue.Queue(maxsize=size)
        err = []

        def fill():
            try:
                for d in reader():
                    q.put(d)
            except BaseException as e:   # surface, never truncate
                err.append(e)
            finally:
                q.put(_End)
        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                if err:
                    raise err[0]
                return
            yield e
    return buffered_reader


def firstn(reader, n):
    """reference: decorator.py:366 — truncate to the first n items."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                return
            yield item
    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """reference: decorator.py:411 — parallel map. Thread-based here (the
    mapper is usually numpy decode work releasing the GIL; true
    multi-process pipelines belong to DataLoader(num_workers=...))."""

    class _End:
        pass

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        errs = []

        def feed():
            try:
                for i, d in enumerate(reader()):
                    in_q.put((i, d))
            except BaseException as e:
                errs.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(_End)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is _End:
                        return
                    i, d = item
                    out_q.put((i, mapper(d)))
            except BaseException as e:
                errs.append(e)
            finally:
                out_q.put(_End)          # always release the consumer

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()
        finished = 0
        pending = {}
        next_i = 0
        while finished < process_num:
            item = out_q.get()
            if item is _End:
                finished += 1
                continue
            i, d = item
            if not order:
                yield d
                continue
            pending[i] = d
            while next_i in pending:
                yield pending.pop(next_i)
                next_i += 1
        if errs:                         # a mapper/reader error surfaces
            raise errs[0]
        if order:
            while next_i in pending:
                yield pending.pop(next_i)
                next_i += 1
    return xreader
