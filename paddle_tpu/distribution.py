"""paddle.distribution (reference: python/paddle/distribution.py —
Distribution/Uniform/Normal/Categorical).

TPU-first: sampling draws from the framework Generator's key stream
(fixed-shape, jit-safe), densities are plain jnp math through the op
dispatch funnel. The reference's Categorical quirk is preserved
faithfully: ``entropy``/``kl_divergence`` treat the input as LOGITS
(softmax), while ``probs``/``log_prob``/``sample`` normalise by the SUM
(distribution.py:640 — the v2.0 behaviour, inconsistent but pinned by
its published examples).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .core import generator as _gen
from .core.tensor import Tensor
from .ops.dispatch import apply

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _raw(x, dtype=jnp.float32):
    if isinstance(x, Tensor):
        return x._data.astype(dtype)
    return jnp.asarray(np.asarray(x), dtype)


class Distribution:
    """Base class (reference: distribution.py:41)."""

    def sample(self, shape=(), seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError

    def _key(self, seed):
        return (jax.random.PRNGKey(int(seed)) if seed
                else _gen.next_key())


class Uniform(Distribution):
    """reference: distribution.py:168 — U[low, high) with broadcasting."""

    def __init__(self, low, high, name=None):
        self.low = _raw(low)
        self.high = _raw(high)

    def sample(self, shape=(), seed=0):
        key = self._key(seed)
        base = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        full = tuple(shape) + base

        def impl(lo, hi):
            u = jax.random.uniform(key, full)
            return lo + (hi - lo) * u
        return apply("uniform_sample", impl, self.low, self.high)

    def entropy(self):
        return apply("uniform_entropy", lambda lo, hi: jnp.log(hi - lo),
                     self.low, self.high)

    def log_prob(self, value):
        def impl(v, lo, hi):
            inside = ((v >= lo) & (v < hi)).astype(v.dtype)
            return jnp.log(inside) - jnp.log(hi - lo)
        return apply("uniform_log_prob", impl, value, self.low, self.high)

    def probs(self, value):
        def impl(v, lo, hi):
            inside = ((v >= lo) & (v < hi)).astype(v.dtype)
            return inside / (hi - lo)
        return apply("uniform_probs", impl, value, self.low, self.high)


class Normal(Distribution):
    """reference: distribution.py:390 — N(loc, scale) with
    broadcasting, KL to another Normal."""

    def __init__(self, loc, scale, name=None):
        self.loc = _raw(loc)
        self.scale = _raw(scale)

    def sample(self, shape=(), seed=0):
        key = self._key(seed)
        base = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        full = tuple(shape) + base

        def impl(mu, sig):
            return mu + sig * jax.random.normal(key, full)
        return apply("normal_sample", impl, self.loc, self.scale)

    def entropy(self):
        def impl(mu, sig):
            base = jnp.zeros(jnp.broadcast_shapes(mu.shape, sig.shape),
                             mu.dtype)
            return base + 0.5 + 0.5 * np.log(2 * np.pi) + jnp.log(sig)
        return apply("normal_entropy", impl, self.loc, self.scale)

    def log_prob(self, value):
        def impl(v, mu, sig):
            var = sig * sig
            return (-((v - mu) ** 2) / (2 * var) - jnp.log(sig)
                    - 0.5 * np.log(2 * np.pi))
        return apply("normal_log_prob", impl, value, self.loc, self.scale)

    def probs(self, value):
        def impl(v, mu, sig):
            var = sig * sig
            return (jnp.exp(-((v - mu) ** 2) / (2 * var))
                    / (sig * np.sqrt(2 * np.pi)))
        return apply("normal_probs", impl, value, self.loc, self.scale)

    def kl_divergence(self, other: "Normal"):
        def impl(mu1, sig1, mu2, sig2):
            ratio = sig1 / sig2
            t1 = ((mu1 - mu2) / sig2) ** 2
            return 0.5 * (ratio * ratio + t1 - 1.0
                          - 2.0 * jnp.log(ratio))
        return apply("normal_kl", impl, self.loc, self.scale,
                     other.loc, other.scale)


class Categorical(Distribution):
    """reference: distribution.py:640. Faithful to the v2.0 semantics:
    entropy/kl use softmax(logits); probs/log_prob/sample normalise the
    (non-negative) logits by their sum — see the reference's own
    docstring examples, which pin both behaviours."""

    def __init__(self, logits, name=None):
        self.logits = _raw(logits)

    @staticmethod
    def _sum_norm(logits):
        """The shared sum-normalisation (the pinned v2.0 quirk)."""
        return logits / jnp.sum(logits, axis=-1, keepdims=True)

    def sample(self, shape=(), seed=0):
        key = self._key(seed)

        def impl(logits):
            p = self._sum_norm(logits)
            # default int dtype: requesting int64 under jax's default
            # x64-off config truncates with a warning on every call
            return jax.random.categorical(
                key, jnp.log(jnp.maximum(p, 1e-30)),
                shape=tuple(shape) + logits.shape[:-1])
        return apply("categorical_sample", impl, self.logits)

    def entropy(self):
        def impl(logits):
            lse = jax.nn.log_softmax(logits, axis=-1)
            p = jnp.exp(lse)
            return -jnp.sum(p * lse, axis=-1)
        return apply("categorical_entropy", impl, self.logits)

    def kl_divergence(self, other: "Categorical"):
        def impl(a, b):
            la = jax.nn.log_softmax(a, axis=-1)
            lb = jax.nn.log_softmax(b, axis=-1)
            return jnp.sum(jnp.exp(la) * (la - lb), axis=-1)
        return apply("categorical_kl", impl, self.logits, other.logits)

    @staticmethod
    def _gather(p, v):
        """1-D logits: fancy-index every value. Batched logits [B, K]:
        per-row gather (reference index_sample semantics), value [B]."""
        v = v.astype(jnp.int32)
        if p.ndim == 1:
            return p[v]
        return jnp.take_along_axis(p, v[..., None], axis=-1)[..., 0]

    def probs(self, value):
        def impl(logits, v):
            return self._gather(self._sum_norm(logits), v)
        return apply("categorical_probs", impl, self.logits, value)

    def log_prob(self, value):
        def impl(logits, v):
            return jnp.log(self._gather(self._sum_norm(logits), v))
        return apply("categorical_log_prob", impl, self.logits, value)
