"""Retry/backoff, deadlines, and deterministic fault injection.

The substrate for the elastic fault-tolerance runtime (see
docs/fault_tolerance.md): transient-error retry with exponential backoff +
jitter, wall-clock deadlines, and an env-driven ``FaultInjector`` that lets
tests kill trainers and corrupt checkpoints at exact, reproducible points.

Pure stdlib on purpose — this module is imported from the pre-backend
bootstrap path and from the launcher supervisor, neither of which may touch
JAX.
"""
from __future__ import annotations

import functools
import os
import random
import sys
import time
from typing import Callable, Optional, Sequence, Tuple, Type


class RetryError(RuntimeError):
    """All attempts exhausted; ``__cause__`` is the last exception."""


class DeadlineExceeded(TimeoutError):
    pass


class FaultInjected(RuntimeError):
    """Raised by FaultInjector for the ``raise`` action."""


#: exit code of a FaultInjector ``crash`` action — a simulated hard crash;
#: the elastic supervisor counts it against the restart budget.
FAULT_CRASH_EXIT_CODE = 43


class Deadline:
    """A wall-clock budget. ``clock`` is injectable so tests never sleep."""

    def __init__(self, seconds: Optional[float], clock=time.monotonic):
        self._clock = clock
        self.seconds = None if seconds is None else float(seconds)
        self._t0 = clock()

    @classmethod
    def from_env(cls, var: str, default: Optional[float] = None, **kw):
        raw = os.environ.get(var)
        return cls(float(raw) if raw not in (None, "") else default, **kw)

    def remaining(self) -> float:
        if self.seconds is None:
            return float("inf")
        return self.seconds - (self._clock() - self._t0)

    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, what: str = "operation"):
        if self.expired():
            raise DeadlineExceeded(
                f"{what} exceeded its {self.seconds}s deadline")


def retry_call(fn: Callable, args=(), kwargs=None, *,
               max_attempts: int = 3,
               backoff: float = 0.5,
               multiplier: float = 2.0,
               max_backoff: float = 30.0,
               jitter: float = 0.1,
               retry_on: Tuple[Type[BaseException], ...] = (Exception,),
               deadline: Optional[Deadline] = None,
               sleep=time.sleep,
               rng=random.random,
               on_retry: Optional[Callable] = None):
    """Call ``fn`` with exponential backoff + jitter between failures.

    Attempts stop at ``max_attempts`` (or when ``deadline`` expires, if one
    is given) and the last exception is re-raised wrapped in
    :class:`RetryError`. ``sleep``/``rng`` are injectable so unit tests run
    with a fake clock and deterministic jitter.
    """
    kwargs = kwargs or {}
    delay = backoff
    last = None
    for attempt in range(1, max(1, int(max_attempts)) + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:  # noqa: PERF203 — the whole point
            last = e
            out_of_time = deadline is not None and deadline.expired()
            if attempt >= max_attempts or out_of_time:
                break
            pause = delay * (1.0 + jitter * (2.0 * rng() - 1.0))
            if deadline is not None:
                pause = min(pause, max(0.0, deadline.remaining()))
            if on_retry is not None:
                on_retry(attempt, e, pause)
            sleep(pause)
            delay = min(delay * multiplier, max_backoff)
    raise RetryError(
        f"{getattr(fn, '__name__', fn)} failed after {attempt} "
        f"attempt(s): {last!r}") from last


def retry(max_attempts: int = 3, backoff: float = 0.5, multiplier: float = 2.0,
          max_backoff: float = 30.0, jitter: float = 0.1,
          retry_on: Tuple[Type[BaseException], ...] = (Exception,),
          sleep=time.sleep, rng=random.random,
          on_retry: Optional[Callable] = None):
    """Decorator form of :func:`retry_call`::

        @retry(max_attempts=3, backoff=0.5)
        def fetch(): ...
    """

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(
                fn, args, kwargs, max_attempts=max_attempts, backoff=backoff,
                multiplier=multiplier, max_backoff=max_backoff, jitter=jitter,
                retry_on=retry_on, sleep=sleep, rng=rng, on_retry=on_retry)
        return wrapper
    return decorator


# -- fault injection ----------------------------------------------------------

class FaultInjector:
    """Deterministic, env-driven fault injection for resilience tests.

    Spec grammar (``PADDLE_TPU_FAULT_SPEC``)::

        spec     := rule ("," rule)*
        rule     := site ":" occurrence ":" action
        site     := identifier           # e.g. epoch, step, save, load
        occurrence := positive integer   # 1-based count of fire(site) calls
        action   := "crash" | "raise" | anything  # others returned verbatim

    Example: ``epoch:3:crash,load:1:corrupt`` — hard-exit the process (code
    :data:`FAULT_CRASH_EXIT_CODE`) on the third ``fire("epoch")`` of this
    process, and hand the string ``"corrupt"`` back to the first
    ``fire("load")`` caller (the checkpoint loader corrupts a shard file and
    then proceeds, so checksum verification can be exercised end to end).

    Verbatim actions interpreted by current call sites: ``corrupt`` at
    ``save``/``load`` (checkpoint shard corruption, sharded.py), ``nan``
    at ``grads``/``loss`` (the numerical-anomaly sentinel poisons the
    corresponding values with NaN right before its health probe —
    ``grads:5:nan`` makes step 5 diverge deterministically), and the
    chaos-campaign actions at the ``ckpt_*`` commit-pipeline sites
    (``incubate.checkpoint.async_ckpt``): ``torn_write`` truncates the
    staged shard archive after checksumming, ``disk_full`` raises
    ``ENOSPC``, ``slow_io`` stalls the writer
    (``PADDLE_TPU_FAULT_SLOW_IO_S`` seconds). ``kill_during_commit`` is an
    alias of ``crash`` (hard ``os._exit``), named so chaos specs read as
    intent.

    Serving-fleet sites (``serving.replica`` / ``serving.fleet.swap``):
    ``replica_boot`` fires once per replica engine construction — initial
    Router boot AND every resurrection/scale-up reboot count, so with 3
    replicas ``replica_boot:4:disk_full`` hits the first scale-up boot
    with ``ENOSPC`` (actions: ``fail`` raises RuntimeError, ``disk_full``
    raises ENOSPC, ``slow_io`` stalls the boot). ``weight_swap`` fires
    once per replica inside a hot-swap roll: ``fail``/``disk_full`` force
    the swap's rollback path, ``slow_io`` stretches the swap window while
    traffic is paused.

    Sequence-migration sites (``serving.llm.scheduler`` /
    ``serving.fleet.migrate`` — docs/fault_tolerance.md, "Zero-loss
    serving"): ``seq_export`` fires once per replica export (park, swap
    migrate-out) — any failure action makes the export raise and the
    caller falls back to the old drain-and-wait path, ``slow_io`` stalls
    the export; ``seq_import`` fires once per sequence adoption on the
    target — any failure action refuses the import and the migrator
    tries the next sibling, then the re-prefill replay path; and
    ``journal_write`` fires once per journal flush — ``drop`` keeps the
    previous (stale) records so recovery must regenerate and verify the
    gap, ``fail``/``disk_full`` count write errors, ``slow_io`` stalls
    the flusher thread. None of these can drop a sequence: every
    failure path degrades to replay, whose dedup guard arbitrates.

    Host-loss sites (``distributed.elastic_runtime``): ``host_kill`` fires
    at watchdog arm time, once per guarded step — ``crash`` there is the
    canonical host-dies-mid-step. ``collective_hang`` fires right after
    arming; ``hang`` sleeps ``PADDLE_TPU_FAULT_HANG_S`` seconds (default
    3600) inside the armed window, the peer-death stall the watchdog must
    convert to exit 121. ``heartbeat_partition`` fires per heartbeat
    beat; ``drop`` latches the sender silent so the coordinator declares
    the host dead while the process lives (the partition case).
    ``slow_link`` delays one beat by ``PADDLE_TPU_FAULT_SLOW_LINK_S``
    seconds (default 2.0) — a blip that must NOT trip the miss
    threshold. See docs/fault_tolerance.md for the full site catalog.

    Counters are per-process: a restarted trainer starts counting from zero
    again, which is exactly what makes "crash once, then succeed" scenarios
    expressible with a single rule. Duplicate ``site:occurrence`` pairs are
    rejected — only one action can win a given firing, and silently keeping
    the first (or last) makes the loser impossible to debug.
    """

    def __init__(self, spec: Optional[str] = None):
        if spec is None:
            spec = os.environ.get("PADDLE_TPU_FAULT_SPEC", "")
        self._rules = {}   # site -> list of (occurrence, action)
        self._counts = {}  # site -> fires so far
        for rule in spec.split(","):
            rule = rule.strip()
            if not rule:
                continue
            parts = [p.strip() for p in rule.split(":")]
            if len(parts) != 3 or not all(parts) or not parts[1].isdigit():
                raise ValueError(
                    f"bad PADDLE_TPU_FAULT_SPEC rule {rule!r}; expected "
                    f"site:occurrence:action (e.g. epoch:2:crash)")
            site, occ, action = parts[0], int(parts[1]), parts[2]
            if occ < 1:
                raise ValueError(
                    f"bad PADDLE_TPU_FAULT_SPEC rule {rule!r}: occurrence "
                    f"is 1-based (the first fire is 1); 0 would never fire")
            if any(o == occ for o, _ in self._rules.get(site, ())):
                raise ValueError(
                    f"duplicate PADDLE_TPU_FAULT_SPEC rule for "
                    f"{site}:{occ}: each site:occurrence pair may appear "
                    f"only once")
            self._rules.setdefault(site, []).append((occ, action))

    def armed(self, site: Optional[str] = None) -> bool:
        if site is None:
            return bool(self._rules)
        return site in self._rules

    def fire(self, site: str) -> Optional[str]:
        """Count one occurrence of ``site``; execute/return a matching rule.

        ``crash`` → ``os._exit(FAULT_CRASH_EXIT_CODE)`` (no cleanup, like a
        real kill). ``raise`` → raises :class:`FaultInjected`. Any other
        action string is returned for the call site to interpret
        (e.g. ``corrupt``). Returns None when no rule matches.
        """
        if site not in self._rules:
            return None
        self._counts[site] = self._counts.get(site, 0) + 1
        n = self._counts[site]
        for occ, action in self._rules[site]:
            if occ != n:
                continue
            if action in ("crash", "kill_during_commit"):
                sys.stderr.write(
                    f"[FaultInjector] {action} at {site}:{n}\n")
                sys.stderr.flush()
                os._exit(FAULT_CRASH_EXIT_CODE)
            if action == "raise":
                raise FaultInjected(f"injected fault at {site}:{n}")
            return action
        return None


_INJECTOR: Optional[FaultInjector] = None


def fault_injector() -> FaultInjector:
    """The process-wide injector, parsed once from the environment."""
    global _INJECTOR
    if _INJECTOR is None:
        _INJECTOR = FaultInjector()
    return _INJECTOR


def _reset_fault_injector_for_tests():
    global _INJECTOR
    _INJECTOR = None
