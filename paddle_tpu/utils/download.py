"""Model/dataset download cache (reference: python/paddle/utils/download.py
get_weights_path_from_url :72, get_path_from_url :119 — URL fetch into
~/.cache with md5 verification and archive decompression).

Environments without egress (like this build's CI) get a clear error
instead of a hang; all consumers accept a local ``data_file``/path so
everything works offline with pre-fetched files.
"""
from __future__ import annotations

import hashlib
import os
import shutil
import tarfile
import zipfile

from .resilience import RetryError, retry_call

WEIGHTS_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_HOME", "~/.cache/paddle_tpu"))

DOWNLOAD_RETRIES = int(os.environ.get("PADDLE_TPU_DOWNLOAD_RETRIES", "3"))


class DownloadError(RuntimeError):
    pass


def _md5check(fullname, md5sum=None):
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def _download(url, path, md5sum=None):
    os.makedirs(path, exist_ok=True)
    fname = os.path.split(url)[-1].replace("%2F", "_")
    fullname = os.path.join(path, fname)
    if os.path.exists(fullname) and _md5check(fullname, md5sum):
        return fullname
    import urllib.request

    def _fetch():
        tmp = fullname + ".tmp"
        urllib.request.urlretrieve(url, tmp)
        os.replace(tmp, fullname)

    try:
        # transient network errors get 3 attempts with exponential backoff
        # (resilience.retry_call) before becoming a permanent DownloadError
        retry_call(_fetch, max_attempts=DOWNLOAD_RETRIES, backoff=0.5)
    except RetryError as e:
        cause = e.__cause__ or e
        raise DownloadError(
            f"failed to download {url} after {DOWNLOAD_RETRIES} attempts: "
            f"{cause!r}. This environment may have no network egress — "
            f"fetch the file manually and pass its path (data_file=/path) "
            f"or place it at {fullname}") from cause
    if not _md5check(fullname, md5sum):
        raise DownloadError(f"md5 mismatch for {fullname}")
    return fullname


def _decompress(fname):
    dst = os.path.dirname(fname)
    if tarfile.is_tarfile(fname):
        with tarfile.open(fname) as t:
            t.extractall(dst)
            names = t.getnames()
        root = names[0].split("/")[0] if names else ""
        return os.path.join(dst, root)
    if zipfile.is_zipfile(fname):
        with zipfile.ZipFile(fname) as z:
            z.extractall(dst)
            names = z.namelist()
        root = names[0].split("/")[0] if names else ""
        return os.path.join(dst, root)
    return fname


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True,
                      decompress=True):
    """reference: utils/download.py:119."""
    root_dir = root_dir or WEIGHTS_HOME
    fullname = _download(url, root_dir, md5sum)
    if decompress and (tarfile.is_tarfile(fullname)
                       or zipfile.is_zipfile(fullname)):
        return _decompress(fullname)
    return fullname


def get_weights_path_from_url(url, md5sum=None):
    """reference: utils/download.py:72."""
    return get_path_from_url(url, os.path.join(WEIGHTS_HOME, "weights"),
                             md5sum, decompress=False)


def _check_exists_and_download(path, url, md5sum, name, download):
    """reference: dataset/common.py _check_exists_and_download."""
    if path and os.path.exists(path):
        return path
    if download:
        return get_path_from_url(
            url, os.path.join(WEIGHTS_HOME, "dataset", name), md5sum,
            decompress=False)
    raise ValueError(f"{path} not exists and auto download disabled")
