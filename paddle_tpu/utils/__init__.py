"""paddle.utils (reference: python/paddle/utils/)."""
from . import download  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401


def try_import(module_name, err_msg=None):
    """reference: utils/lazy_import.py try_import."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"{module_name} is required") from e


def run_check():
    """reference: utils/install_check.py run_check — sanity-train a tiny
    model on the visible devices."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer

    paddle.seed(0)
    lin = nn.Linear(4, 2)
    opt = optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    x = paddle.to_tensor(np.ones((8, 4), np.float32))
    losses = []
    for _ in range(2):
        loss = paddle.mean(lin(x) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[1] <= losses[0]
    n = len(jax.devices())
    print(f"paddle_tpu is installed successfully! {n} device(s) "
          f"({jax.devices()[0].platform}) available.")
