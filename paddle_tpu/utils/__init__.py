"""paddle.utils (reference: python/paddle/utils/)."""
from . import download  # noqa: F401
from . import resilience  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401
from .resilience import retry, retry_call, Deadline, FaultInjector  # noqa: F401


def try_import(module_name, err_msg=None):
    """reference: utils/lazy_import.py try_import."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"{module_name} is required") from e


def run_check():
    """reference: utils/install_check.py run_check — sanity-train a tiny
    model on the visible devices."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer

    paddle.seed(0)
    lin = nn.Linear(4, 2)
    opt = optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    x = paddle.to_tensor(np.ones((8, 4), np.float32))
    losses = []
    for _ in range(2):
        loss = paddle.mean(lin(x) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[1] <= losses[0]
    n = len(jax.devices())
    print(f"paddle_tpu is installed successfully! {n} device(s) "
          f"({jax.devices()[0].platform}) available.")


# -- reference utils/__init__.py export tail ---------------------------------

import functools as _functools
import warnings as _warnings


def deprecated(update_to="", since="", reason=""):
    """reference: utils/deprecated.py — decorator emitting a
    DeprecationWarning once per call site."""

    def decorator(fn):
        @_functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = (f"API '{getattr(fn, '__name__', fn)}' is deprecated "
                   f"since {since or 'this release'}")
            if update_to:
                msg += f", use '{update_to}' instead"
            if reason:
                msg += f". Reason: {reason}"
            _warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return decorator


def require_version(min_version, max_version=None):
    """reference: utils/__init__.py require_version — version gate
    against paddle.version."""
    from .. import version as _v

    def to_tuple(s):
        return tuple(int(x) for x in str(s).split(".")[:3])
    cur = to_tuple(_v.full_version)
    if to_tuple(min_version) > cur:
        raise Exception(
            f"installed version {_v.full_version} < required minimum "
            f"{min_version}")
    if max_version is not None and to_tuple(max_version) < cur:
        raise Exception(
            f"installed version {_v.full_version} > required maximum "
            f"{max_version}")


class _UniqueName:
    """reference: fluid/unique_name.py — name generator + guard."""

    def __init__(self):
        self._counters = {}

    def generate(self, key):
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return f"{key}_{n}"

    def guard(self, new_generator=None):
        import contextlib

        @contextlib.contextmanager
        def _g():
            saved = dict(self._counters)
            if new_generator is not None:
                self._counters.clear()
            try:
                yield
            finally:
                self._counters.clear()
                self._counters.update(saved)
        return _g()


unique_name = _UniqueName()

from ..profiler import Profiler  # noqa: E402,F401


class ProfilerOptions:
    """reference: utils/profiler.py ProfilerOptions — config holder for
    the legacy profiler; the jax-backed Profiler takes log_dir only."""

    def __init__(self, options=None):
        self.options = options or {}


def get_profiler(options=None):
    return Profiler()


class OpLastCheckpointChecker:
    """reference: utils/op_version.py — queries op version checkpoints
    compiled into the C++ core. No C++ op registry exists here; every op
    is at its initial version."""

    def get_op_attrs(self, op_name):
        return []


def cpp_extension(*a, **k):
    raise RuntimeError(
        "paddle.utils.cpp_extension builds pybind11 CUDA/C++ ops; this "
        "TPU build's native extension points are "
        "ops.custom.register_custom_op (host C/C++ via ctypes, see "
        "csrc/) and register_pallas_op (TPU kernels); see also "
        "paddle.sysconfig.get_include()")


from ..vision import image as image_util  # noqa: E402,F401
