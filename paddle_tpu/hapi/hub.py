"""paddle.hub: load models from hubconf.py entrypoints
(reference: python/paddle/hapi/hub.py — list/help/load over github, gitee
or local sources; remote archives fetched via utils/download.py).

Offline-first: ``source='local'`` is fully functional; remote sources go
through paddle_tpu.utils.download and raise a clear error without egress.
"""
from __future__ import annotations

import importlib.util
import os
import sys

MODULE_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _resolve_dir(repo_dir, source):
    if source == "local":
        return repo_dir
    from ..utils.download import get_path_from_url
    if source == "github":
        repo, _, branch = repo_dir.partition(":")
        branch = branch or "main"
        url = f"https://github.com/{repo}/archive/{branch}.zip"
    elif source == "gitee":
        repo, _, branch = repo_dir.partition(":")
        branch = branch or "main"
        url = f"https://gitee.com/{repo}/repository/archive/{branch}.zip"
    else:
        raise ValueError(f"unknown hub source {source!r}")
    return get_path_from_url(url)


def list(repo_dir, source="github", force_reload=False):  # noqa: A001
    """reference: hapi/hub.py list — callable entrypoint names."""
    mod = _load_hubconf(_resolve_dir(repo_dir, source))
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):  # noqa: A001
    """reference: hapi/hub.py help — the entrypoint's docstring."""
    mod = _load_hubconf(_resolve_dir(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"no entrypoint {model!r} in {repo_dir}")
    return fn.__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """reference: hapi/hub.py load — call the entrypoint."""
    mod = _load_hubconf(_resolve_dir(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"no entrypoint {model!r} in {repo_dir}")
    return fn(**kwargs)
