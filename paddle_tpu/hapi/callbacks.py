"""hapi callbacks (reference: python/paddle/hapi/callbacks.py — Callback,
CallbackList, ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler,
ReduceLROnPlateau, VisualDL)."""
from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kw):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kw)
            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """reference: callbacks.py ProgBarLogger."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._start = time.time()
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            total = f"/{self.steps}" if self.steps else ""
            print(f"  step {step}{total} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dur = time.time() - self._start
            items = ", ".join(f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"  epoch {epoch + 1} done in {dur:.1f}s - {items}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = ", ".join(f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"  eval - {items}")


def _fmt(v):
    if isinstance(v, (list, tuple, np.ndarray)):
        return "[" + ", ".join(f"{float(x):.4f}" for x in np.ravel(v)) + "]"
    try:
        return f"{float(v):.4f}"
    except (TypeError, ValueError):
        return str(v)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and \
                epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.stopped_epoch = 0
        self.wait = 0
        self.best = None
        self.stop_training = False

    def on_eval_end(self, logs=None):
        logs = logs or {}
        v = logs.get(self.monitor)
        if v is None:
            return
        v = float(np.ravel(v)[0]) if isinstance(v, (list, tuple, np.ndarray)) else float(v)
        better = (self.best is None
                  or (self.mode == "min" and v < self.best - self.min_delta)
                  or (self.mode == "max" and v > self.best + self.min_delta))
        if better:
            self.best = v
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                if self.model is not None:
                    self.model.stop_training = True


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference: callbacks.py LRScheduler)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s is not None and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s is not None and self.by_epoch:
            s.step()


class VisualDL(Callback):
    """Metric logging callback. The VisualDL service itself is external; this
    writes a plain JSONL the dashboard (or TensorBoard via adapter) can tail
    (reference: callbacks.py VisualDL)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None
        self._step = 0

    def on_train_begin(self, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(os.path.join(self.log_dir, "metrics.jsonl"), "a")

    def on_train_batch_end(self, step, logs=None):
        import json
        if self._f and logs:
            rec = {"step": self._step,
                   **{k: _scalar(v) for k, v in logs.items()}}
            self._f.write(json.dumps(rec) + "\n")
            self._step += 1

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()
            self._f = None


def _scalar(v):
    try:
        return float(np.ravel(v)[0])
    except (TypeError, ValueError):
        return str(v)


class FaultToleranceCallback(Callback):
    """Preemption-aware checkpointing for ``Model.fit``
    (docs/fault_tolerance.md).

    Arms a :class:`~paddle_tpu.distributed.elastic.PreemptionGuard` (or
    shares one passed in) and polls it every batch and epoch; on preemption
    it commits a final checkpoint to ``save_dir`` and exits with the
    reserved resume code, so ``launch --elastic`` restarts the rank without
    burning the restart budget. Also fires the FaultInjector ``step`` site
    each batch so kill-mid-step scenarios are scriptable in tests
    (``PADDLE_TPU_FAULT_SPEC="step:7:crash"``).

    ``async_save=True`` routes periodic saves through the crash-consistent
    :class:`~paddle_tpu.incubate.checkpoint.async_ckpt.AsyncCheckpointer`
    (sharded format under ``save_dir/<tag>``, atomic commit, overlapped
    with training); the preemption save and ``on_train_end`` drain the
    writer so no snapshot is lost at exit.
    """

    def __init__(self, save_dir, guard=None, save_freq=1, async_save=False,
                 step_watchdog=None):
        super().__init__()
        self.save_dir = save_dir
        self.save_freq = max(1, int(save_freq))
        self._guard = guard
        self._epoch = 0
        self._async_save = bool(async_save)
        self._ckpt = None
        self._watchdog = step_watchdog

    def _ensure_guard(self):
        if self._guard is None:
            from ..distributed.elastic import PreemptionGuard
            self._guard = PreemptionGuard()
        return self._guard

    def on_train_begin(self, logs=None):
        self._ensure_guard()
        # collective watchdog (elastic_runtime), auto-armed from the cohort
        # supervisor's PADDLE_TPU_STEP_DEADLINE_S the way the guard is from
        # PADDLE_TPU_ELASTIC: each train batch is a guarded step, so a peer
        # death mid-collective becomes exit 121 within the deadline
        from ..distributed.elastic_runtime.watchdog import maybe_auto_watchdog
        self._watchdog = maybe_auto_watchdog(self._watchdog)
        if self._async_save and self._ckpt is None:
            from ..incubate.checkpoint.async_ckpt import (
                AsyncCheckpointer, cleanup_stale_staging)
            if self.save_dir:
                os.makedirs(self.save_dir, exist_ok=True)
                cleanup_stale_staging(self.save_dir)
            self._ckpt = AsyncCheckpointer()

    def _ckpt_state(self):
        state = {"model": dict(self.model.network.state_dict())}
        if getattr(self.model, "_optimizer", None) is not None:
            state["optimizer"] = dict(self.model._optimizer.state_dict())
        return state

    def _save(self, tag, drain=False):
        if self.model is None or not self.save_dir:
            return
        os.makedirs(self.save_dir, exist_ok=True)
        if self._ckpt is not None:
            self._ckpt.save(self._ckpt_state(),
                            os.path.join(self.save_dir, tag),
                            step=self._epoch)
            if drain:
                self._ckpt.wait()
        else:
            self.model.save(os.path.join(self.save_dir, tag))

    def restore(self, tag="latest"):
        """Load an async-saved sharded checkpoint back into the model (the
        counterpart of ``Model.load`` for ``async_save=True`` saves)."""
        from ..incubate.checkpoint.sharded import load_sharded
        state = load_sharded(os.path.join(self.save_dir, tag))
        self.model.network.set_state_dict(state["model"])
        if ("optimizer" in state
                and getattr(self.model, "_optimizer", None) is not None):
            self.model._optimizer.set_state_dict(state["optimizer"])

    def _poll(self):
        guard = self._ensure_guard()
        if guard.preempted:
            # the final checkpoint must be durable before the exit, so the
            # async path drains the writer inside the save_fn
            guard.exit_if_preempted(
                save_fn=lambda: self._save("preempted", drain=True))

    def on_train_batch_begin(self, step, logs=None):
        if self._watchdog is not None:
            self._watchdog.arm(step)

    def on_train_batch_end(self, step, logs=None):
        if self._watchdog is not None:
            self._watchdog.disarm()
        from ..utils.resilience import fault_injector
        fault_injector().fire("step")
        self._poll()

    def on_epoch_end(self, epoch, logs=None):
        self._epoch = epoch
        if epoch % self.save_freq == 0:
            self._save("latest")
        self._poll()

    def on_train_end(self, logs=None):
        if self._watchdog is not None:
            self._watchdog.disarm()
        if self._ckpt is not None:
            self._ckpt.wait()


class AnomalyGuardCallback(Callback):
    """Numerical-anomaly guarding for ``Model.fit``
    (docs/fault_tolerance.md, "Numerical faults").

    Wires the :mod:`paddle_tpu.sentinel` stack into the fit loop:

    - attaches a :class:`~paddle_tpu.sentinel.Sentinel` to the model's
      optimizer, so NaN/Inf gradients are caught *before* the update by
      the fused on-device probe (one scalar fetch per guarded step) and
      the poisoned update is skipped;
    - feeds each batch's logged loss into the EWMA z-score spike detector
      (``Sentinel.feed_loss`` — no extra host syncs, the fit loop fetched
      that float anyway);
    - keeps health-stamped rollback snapshots under
      ``save_dir/snapshots`` every ``snapshot_freq`` epochs (an epoch that
      saw anomalies is stamped unhealthy, so the ``rollback`` rung never
      restores into the divergence it is escaping);
    - on escalation: quarantines the offending batch under
      ``save_dir/quarantine``, rolls back, or halts with
      ``DIVERGENCE_EXIT_CODE`` per the configured ladder.
    """

    def __init__(self, save_dir=None, config=None, snapshot_freq=1,
                 keep_last=2, attach_optimizer=True, async_snapshots=False):
        super().__init__()
        self.save_dir = save_dir
        self.snapshot_freq = max(1, int(snapshot_freq))
        self.keep_last = keep_last
        self.attach_optimizer = attach_optimizer
        self.async_snapshots = bool(async_snapshots)
        self._config = config
        self.sentinel = None
        self.rollback = None
        self._epoch_anomalies = 0

    def on_train_begin(self, logs=None):
        from ..sentinel import (Sentinel, SentinelConfig, CheckpointRollback)
        if self.sentinel is None:
            cfg = self._config
            if cfg is None:
                qdir = (os.path.join(self.save_dir, "quarantine")
                        if self.save_dir else None)
                cfg = SentinelConfig(quarantine_dir=qdir)
            if self.save_dir:
                self.rollback = CheckpointRollback(
                    os.path.join(self.save_dir, "snapshots"),
                    model=self.model.network,
                    optimizer=self.model._optimizer,
                    keep_last=self.keep_last,
                    async_save=self.async_snapshots)
            self.sentinel = Sentinel(cfg, rollback=self.rollback)
            self.sentinel.batch_getter = \
                lambda: getattr(self.model, "_last_batch", None)
        if self.attach_optimizer and self.model._optimizer is not None:
            self.sentinel.attach(self.model._optimizer)

    def on_epoch_begin(self, epoch, logs=None):
        if self.sentinel is not None:
            self._epoch_anomalies = self.sentinel.anomalies

    def on_train_batch_end(self, step, logs=None):
        loss = (logs or {}).get("loss")
        if self.sentinel is not None and loss is not None:
            loss = loss[0] if isinstance(loss, (list, tuple)) else loss
            self.sentinel.feed_loss(np.asarray(loss).reshape(-1)[0])

    def on_epoch_end(self, epoch, logs=None):
        if self.rollback is None or epoch % self.snapshot_freq != 0:
            return
        healthy = (self.sentinel.anomalies == self._epoch_anomalies)
        self.rollback.snapshot(
            self.sentinel._step, healthy=healthy,
            reason=None if healthy else
            f"epoch {epoch} saw "
            f"{self.sentinel.anomalies - self._epoch_anomalies} anomalies")

    def on_train_end(self, logs=None):
        if self.rollback is not None:
            self.rollback.wait()  # async snapshots must land before exit


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                    "metrics": metrics or []})
    return lst
