"""paddle.Model: the Keras-like high-level API.

Reference: python/paddle/hapi/model.py:876 Model (fit :1519, evaluate,
predict, save/load, summary; DynamicGraphAdapter :659 / StaticGraphAdapter
:250). TPU design: one adapter — the train step is functionalized and
jit-compiled whole (forward + loss + backward + optimizer update in a single
XLA program, buffers donated), which is the role the StaticGraphAdapter's
compiled Program served, with the dygraph API surface.
"""
from __future__ import annotations

import time as _time
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter, stable_uid
from ..core import generator as _gen
from ..core import autograd_engine as _ag
from ..nn.layer_base import Layer
from ..metric import Metric
from ..io import DataLoader, Dataset
from ..jit.functionalize import trace_context, swap_params
from ..observability import tracer as _otrace
from .callbacks import config_callbacks
from .. import framework_io



def _mark_first_compile(tag, jitted):
    """Wrap a jitted callable so its first invocation — where jax traces,
    lowers and compiles — lands on the span timeline as ``jit/compile``.
    Later calls pay one list check (~ns against a ms-scale step)."""
    done = []

    def call(*args):
        if not done:
            done.append(1)
            with _otrace.span("jit/compile", {"fn": tag}):
                return jitted(*args)
        return jitted(*args)

    return call


def _effect_fixed_indices(ts):
    """Positions (within the fixed-buffer list) of every state-effect
    holder, or None when some holder is not a registered non-trainable
    buffer (e.g. set_value on an ad-hoc Tensor during forward) — callers
    must then fall back to the per-step train_batch path, which applies
    effects by identity without needing positions."""
    holders = ts["meta"].get("effect_holders", [])
    id2pos = {id(t): i for i, t in enumerate(ts["state"])}
    fixed_of = {p: j for j, p in enumerate(ts["fixed_pos"])}
    out = []
    for h in holders:
        pos = id2pos.get(id(h))
        if pos is None or pos not in fixed_of:
            return None
        out.append(fixed_of[pos])
    return out


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False
        self._eval_fns_max = 64         # LRU bound (cf. dispatch cache)
        self._step_meter = None         # opt-in MFU meter (attach_step_meter)
        self._invalidate_compiled()

    def attach_step_meter(self, meter=None):
        """Opt into live MFU accounting: publishes ``train.mfu`` /
        ``train.flops_per_step`` / ``train.step_ms`` per train_batch.
        FLOPs come from one extra XLA cost-analysis compile per train-step
        signature (docs/observability.md)."""
        if meter is None:
            from ..observability.stepmeter import StepMeter
            meter = StepMeter(prefix="train")
        self._step_meter = meter
        return meter

    def _invalidate_compiled(self):
        """Drop every compiled program. The step/loop closures capture the
        optimizer's update rule, clip/decay vectors and its _state dict;
        the eval programs capture the loss; all of them capture parameter
        objects — any of prepare()/load() invalidates them or a stale
        program keeps running with the old configuration."""
        self._train_step_fn = None
        self._train_sig = None
        self._fused_loop_key = None
        self._fused_loop = None
        self._multi_step_key = None
        self._multi_step_fn = None
        from collections import OrderedDict
        self._eval_fns = OrderedDict()  # (sig, mode) -> compiled program
        # sig -> compiled train step; bounded LRU so size-bucketed
        # multi-scale training (YOLO) switches buckets without recompiling
        self._train_fns = OrderedDict()

    def _get_train_step(self, sig):
        ts = self._train_fns.get(sig)
        if ts is None:
            self.network.train()
            ts = self._build_train_step(sig)
            if len(self._train_fns) >= 16:
                self._train_fns.popitem(last=False)
            self._train_fns[sig] = ts
        else:
            self._train_fns.move_to_end(sig)
        self._train_step_fn = ts
        self._train_sig = sig
        return ts

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._invalidate_compiled()
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)

    # ------------------------------------------------------------------
    def _state(self):
        ps = [p for _, p in self.network.named_parameters()]
        bs = [b for _, b in self.network.named_buffers()]
        return ps, bs

    def _build_train_step(self, sig):
        """Compile (params, opt_state, x, y, key, lr, step) -> (loss, preds,
        new_params, new_state, effects) — one XLA program per signature."""
        params, buffers = self._state()
        state = params + buffers
        trainable = [p for p in params if not p.stop_gradient]
        t_pos = [i for i, p in enumerate(state) if not p.stop_gradient
                 and i < len(params)]
        fixed_pos = [i for i in range(len(state)) if i not in set(t_pos)]
        opt = self._optimizer
        loss_fn = self._loss
        net = self.network
        reg_coeffs = [opt._regularized_grad(p, None) for p in trainable]
        clip = opt._grad_clip
        ctxs = opt._param_update_ctx(trainable)

        meta = {}

        # materializing predictions is an extra HBM write per step (a
        # [B, S, vocab] logits tensor for LM heads); skip it when no
        # metric consumes them
        want_preds = bool(self._metrics)

        def fwd_loss(train_raws, fixed_raws, x_raws, y_raws, key):
            full = [None] * len(state)
            for pos, r in zip(fixed_pos, fixed_raws):
                full[pos] = r
            for pos, r in zip(t_pos, train_raws):
                full[pos] = r
            with trace_context(key) as ctx:
                with swap_params(state, full):
                    with _ag.no_grad():
                        xs = [Tensor(r) for r in x_raws]
                        ys = [Tensor(r) for r in y_raws]
                        preds = net.forward(*xs)
                        preds_t = preds if isinstance(preds, (list, tuple)) \
                            else [preds]
                        loss = loss_fn(*preds_t, *ys)
                effects = [r for _, r in ctx.state_effects]
                meta["effect_holders"] = [h for h, _ in ctx.state_effects]
            loss_raw = loss._data if isinstance(loss, Tensor) else loss
            out_preds = [p._data for p in preds_t] if want_preds else []
            return loss_raw, (out_preds, effects)

        def step(train_raws, fixed_raws, opt_states, x_raws, y_raws, key, lr,
                 step_no):
            (loss, (preds, effects)), grads = jax.value_and_grad(
                fwd_loss, has_aux=True)(train_raws, fixed_raws, x_raws,
                                        y_raws, key)
            grads = list(grads)
            # clip first, then regularize — same order as Optimizer.step
            if clip is not None:
                grads = clip._clip_raw(trainable, grads)
            for i, rc in enumerate(reg_coeffs):
                if rc is not None:
                    grads[i] = grads[i] + rc * train_raws[i]
            new_p, new_s = [], []
            for pr, g, st, ctx in zip(train_raws, grads, opt_states, ctxs):
                p2, s2 = opt._update(pr, g.astype(pr.dtype), st, lr, step_no,
                                     ctx)
                new_p.append(p2)
                new_s.append(s2)
            return loss, preds, new_p, new_s, effects

        def grads_only(train_raws, fixed_raws, x_raws, y_raws, key):
            # update=False form (gradient accumulation): raw grads, no
            # clip/regularize/update — those belong to the eventual step
            (loss, (preds, effects)), grads = jax.value_and_grad(
                fwd_loss, has_aux=True)(train_raws, fixed_raws, x_raws,
                                        y_raws, key)
            return loss, preds, list(grads), effects

        jitted = jax.jit(step, donate_argnums=(0, 2))
        return {"fn": _mark_first_compile("train_step", jitted),
                "grads_fn": _mark_first_compile("train_grads",
                                                jax.jit(grads_only)),
                "raw_step": step, "fwd_loss": fwd_loss, "meta": meta,
                "state": state, "trainable": trainable, "t_pos": t_pos,
                "fixed_pos": fixed_pos}

    def _prepare_multi_step(self, name, inputs, labels):
        """Shared preamble of train_batches/train_loop: normalize stacked
        inputs, (re)build the compiled step for the per-step signature,
        init optimizer state, reject configurations the multi-step paths
        cannot honor, and make sure effect metadata exists."""
        if self._metrics:
            raise ValueError(
                f"{name}: detach metrics (prepare(..., metrics=None)); "
                "per-step predictions are not materialized")
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        xs = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
              for i in inputs]
        ys = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
              for l in labels]
        K = int(xs[0].shape[0])
        # per-step signature drives the same compiled-step cache
        sig = (tuple((tuple(r.shape[1:]), str(r.dtype)) for r in xs + ys),
               False)
        ts = self._get_train_step(sig)
        opt = self._optimizer
        if any(p._grad is not None for p in ts["trainable"]):
            raise RuntimeError(
                f"{name}: pending accumulated gradients from "
                "train_batch(update=False); finish the accumulation window "
                "with train_batch(update=True) first")
        for p in ts["trainable"]:
            if stable_uid(p) not in opt._state:
                opt._state[stable_uid(p)] = opt._init_state(p)
        opt._accumulators_built = True
        if "effect_holders" not in ts["meta"]:
            # one abstract evaluation populates meta (no compile)
            opt_states = [opt._state[stable_uid(p)] for p in ts["trainable"]]
            sds = lambda r: jax.ShapeDtypeStruct(r.shape, r.dtype)
            jax.eval_shape(
                ts["raw_step"],
                [sds(p._data) for p in ts["trainable"]],
                [sds(ts["state"][i]._data) for i in ts["fixed_pos"]],
                jax.tree_util.tree_map(sds, opt_states),
                [sds(x[0]) for x in xs], [sds(y[0]) for y in ys],
                jax.ShapeDtypeStruct((2,), np.uint32),
                jax.ShapeDtypeStruct((), np.float32),
                jax.ShapeDtypeStruct((), np.float32))
        return ts, opt, xs, ys, K

    def train_batches(self, inputs, labels=None):
        """Run K fused train steps in ONE compiled program.

        ``inputs``/``labels`` carry a leading steps axis ([K, batch, ...]
        per tensor). The K-step loop runs as one on-device ``lax.scan`` —
        one host dispatch instead of K, the TPU analog of the reference's
        C++ executor owning the whole train loop (fluid Executor.run
        executes the full Program per call; here the program IS K steps).

        BN running stats and other state effects thread through the scan
        carry, so K calls of :meth:`train_batch` and one call of
        ``train_batches`` compute identical state (pinned by
        tests/test_train_multi_step.py). Note the rolled scan pays
        per-iteration carry copies for the donated parameter buffers —
        on big models per-step :meth:`train_batch` dispatch is usually as
        fast or faster (measured: docs/perf_notes.md round 4); this API
        is about dispatch-count, not step time. Not available while
        metrics are attached (per-step predictions are not materialized).
        Returns the list of K losses.
        """
        ts, opt, xs, ys, K = self._prepare_multi_step(
            "train_batches", inputs, labels)
        opt_states = [opt._state[stable_uid(p)] for p in ts["trainable"]]
        train_raws = [p._data for p in ts["trainable"]]
        fixed_raws = [ts["state"][i]._data for i in ts["fixed_pos"]]
        keys = jnp.stack([_gen.next_key() for _ in range(K)])
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        step0 = jnp.asarray(opt._global_step + 1, jnp.float32)
        eff_idx = _effect_fixed_indices(ts)
        if eff_idx is None:
            raise ValueError(
                "train_batches: the forward records state effects on "
                "tensors that are not registered buffers; the scan cannot "
                "thread them — use train_batch")
        mk = (self._train_sig, K)
        if getattr(self, "_multi_step_key", None) != mk:
            self._multi_step_fn = self._build_multi_step(ts)
            self._multi_step_key = mk
        losses, new_p, new_fixed, new_s = self._multi_step_fn(
            train_raws, fixed_raws, opt_states, xs, ys, keys, lr, step0)
        for p, npr, ns in zip(ts["trainable"], new_p, new_s):
            p._data = npr
            p._inplace_version += 1
            opt._state[stable_uid(p)] = ns
        holders = ts["meta"].get("effect_holders", [])
        for h, fj in zip(holders, eff_idx):
            h._data = new_fixed[fj]
            h._inplace_version += 1
        opt._global_step += K
        return [float(v) for v in np.asarray(losses)]

    def _build_multi_step(self, ts):
        """jit( scan over raw_step ) with BN/state effects threaded
        through the carry."""
        step = ts["raw_step"]
        eff_fixed_idx = _effect_fixed_indices(ts) or []

        def multi(train_raws, fixed_raws, opt_states, xs, ys, keys, lr,
                  step0):
            def body(carry, inp):
                tr, fx, st, i = carry
                x_sl, y_sl, key = inp
                loss, _preds, tr, st, effects = step(
                    list(tr), list(fx), list(st), list(x_sl), list(y_sl),
                    key, lr, step0 + i)
                fx = list(fx)
                for j, e in zip(eff_fixed_idx, effects):
                    fx[j] = e
                return (tuple(tr), tuple(fx), tuple(st), i + 1.0), loss
            init = (tuple(train_raws), tuple(fixed_raws), tuple(opt_states),
                    jnp.asarray(0.0, jnp.float32))
            # rolled scan only: unroll=True produced WRONG parameter
            # updates for K >= 3 with donated buffers (XLA aliasing across
            # the unrolled iterations; reproduced in
            # tests/test_train_multi_step.py history) — and measured no
            # faster anyway once compile time is counted
            (tr, fx, st, _), losses = jax.lax.scan(
                body, init, (tuple(xs), tuple(ys), keys))
            return losses, list(tr), list(fx), list(st)
        return jax.jit(multi, donate_argnums=(0, 2))

    def train_loop(self, inputs, labels=None):
        """Coalesced multi-step training (reference:
        operators/coalesce_tensor_op.cc + the fused optimizer family,
        operators/optimizers/distributed_fused_lamb*).

        ``inputs``/``labels`` carry a leading steps axis ([K, batch, ...]).
        Trainable parameters and optimizer states are packed ONCE into one
        flat buffer per dtype, the per-step program takes ~6 device arrays
        instead of ~600, and state unpacks at loop exit. With hundreds of
        parameter buffers, per-step dispatch through the device transport
        costs ~10 ms/step (measured, BERT-base through the axon tunnel);
        this path removes it while keeping step math identical — the flat
        buffer is sliced back into per-parameter views inside the trace,
        and elementwise optimizers (SGD/Momentum/Adam/AdamW) apply
        directly on the flat buffers with per-element decay/clip masks.

        Falls back to per-step :meth:`train_batch` calls when the
        optimizer/clip configuration is not elementwise-safe (per-param
        trust ratios, non-global-norm clips, multi_precision masters).
        Returns the list of K losses.
        """
        ts, opt, xs, ys, K = self._prepare_multi_step(
            "train_loop", inputs, labels)

        fused = self._build_fused_loop(ts)
        if fused is None:
            out = []
            for k in range(K):
                loss, _ = self.train_batch([x[k] for x in xs],
                                           [y[k] for y in ys])
                out.append(loss)
            return out
        pack, unpack_back, fused_fn, eff_fixed_idx = fused

        train_raws = [p._data for p in ts["trainable"]]
        states = [opt._state[stable_uid(p)] for p in ts["trainable"]]
        fixed = [ts["state"][i]._data for i in ts["fixed_pos"]]
        flat_ps, flat_sts = pack(train_raws, states)
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        losses = []
        for k in range(K):
            step_no = jnp.asarray(opt._global_step + 1 + k, jnp.float32)
            loss, flat_ps, flat_sts, effects = fused_fn(
                flat_ps, fixed, flat_sts, [x[k] for x in xs],
                [y[k] for y in ys], _gen.next_key(), lr, step_no)
            for j, e in zip(eff_fixed_idx, effects):
                fixed[j] = e
            losses.append(loss)
        opt._global_step += K
        unpack_back(flat_ps, flat_sts, fixed)
        return [float(np.asarray(l)) for l in losses]

    def _build_fused_loop(self, ts):
        """Coalesced-buffer step builder; returns None when the optimizer
        or clip configuration is not elementwise-safe on flat buffers."""
        if getattr(self, "_fused_loop_key", None) == self._train_sig:
            return self._fused_loop
        from ..nn.clip import ClipGradByGlobalNorm, _clips
        opt = self._optimizer
        clip = opt._grad_clip
        trainable = ts["trainable"]
        result = None
        while True:  # single-pass "try"; break = fallback
            if not getattr(opt, "_elementwise_update", False):
                break  # LAMB/LARS-style cross-element terms can't coalesce
            if clip is not None and not isinstance(clip,
                                                   ClipGradByGlobalNorm):
                break
            states = [opt._state[stable_uid(p)] for p in trainable]
            key_sets = {tuple(sorted(s.keys())) for s in states}
            if len(key_sets) != 1:
                break
            state_keys = sorted(states[0].keys())
            if "master" in state_keys:
                break  # per-param master copies: layouts diverge
            ctxs = opt._param_update_ctx(trainable)
            ctx_mode = None
            if all(c is None for c in ctxs):
                ctx_mode = "none"
            elif all(isinstance(c, tuple) and len(c) == 2
                     and all(isinstance(v, (int, float)) for v in c)
                     for c in ctxs):
                ctx_mode = "vec2"
            else:
                break
            reg_coeffs = [opt._regularized_grad(p, None) for p in trainable]
            if not all(rc is None or np.isscalar(rc) or getattr(
                    rc, "ndim", 1) == 0 for rc in reg_coeffs):
                break

            # -- group by param dtype ------------------------------------
            groups = {}
            for i, p in enumerate(trainable):
                groups.setdefault(str(p._data.dtype), []).append(i)
            gmeta = []
            for dt, idxs in groups.items():
                offs, n = [], 0
                for i in idxs:
                    sz = int(np.prod(trainable[i]._data.shape)) or 1
                    offs.append((n, sz, tuple(trainable[i]._data.shape)))
                    n += sz
                gmeta.append((dt, idxs, offs, n))

            def vec_of(values, gi, dtype=jnp.float32):
                dt, idxs, offs, n = gmeta[gi]
                v = np.zeros((n,), np.float32)
                for (o, sz, _), i in zip(offs, idxs):
                    v[o:o + sz] = values[i]
                return jnp.asarray(v, dtype)

            reg_vecs, ctx_vecs, clip_masks = [], [], []
            for gi, (dt, idxs, offs, n) in enumerate(gmeta):
                if any(reg_coeffs[i] is not None for i in idxs):
                    reg_vecs.append(vec_of(
                        [float(reg_coeffs[i]) if reg_coeffs[i] is not None
                         else 0.0 for i in range(len(trainable))], gi))
                else:
                    reg_vecs.append(None)
                if ctx_mode == "vec2":
                    c0 = vec_of([float(c[0]) for c in ctxs], gi)
                    c1 = vec_of([float(c[1]) for c in ctxs], gi)
                    ctx_vecs.append((c0, c1))
                else:
                    ctx_vecs.append(None)
                clip_masks.append(vec_of(
                    [1.0 if _clips(p) else 0.0 for p in trainable], gi))

            holders = ts["meta"].get("effect_holders", [])
            eff_fixed_idx = _effect_fixed_indices(ts)
            if eff_fixed_idx is None and holders:
                break  # effects on unregistered tensors: per-step fallback
            eff_fixed_idx = eff_fixed_idx or []
            fwd_loss = ts["fwd_loss"]

            def unpack(flats):
                raws = [None] * len(trainable)
                for (dt, idxs, offs, n), buf in zip(gmeta, flats):
                    for (o, sz, shp), i in zip(offs, idxs):
                        raws[i] = jax.lax.dynamic_slice(
                            buf, (o,), (sz,)).reshape(shp)
                return raws

            def fused_step(flat_ps, fixed_raws, flat_sts, x_raws, y_raws,
                           key, lr, step_no):
                # differentiate w.r.t. the UNPACKED per-param list — the
                # flat buffer stays outside the grad so the transpose is a
                # per-param cotangent list, re-coalesced with one
                # concatenate per group (grad w.r.t. the flat buffer would
                # transpose every slice into a serialized
                # dynamic-update-slice chain over the whole buffer:
                # measured 2.7x slower than the per-step path)
                raws = unpack(flat_ps)

                def loss_over_list(raw_list):
                    return fwd_loss(raw_list, fixed_raws, x_raws,
                                    y_raws, key)
                (loss, (_preds, effects)), grads = jax.value_and_grad(
                    loss_over_list, has_aux=True)(raws)
                flat_grads = []
                for (dt, idxs, offs, n), pbuf in zip(gmeta, flat_ps):
                    flat_grads.append(jnp.concatenate(
                        [grads[i].reshape(-1) for i in idxs]).astype(
                            pbuf.dtype))
                if clip is not None:
                    gn = jnp.sqrt(sum(
                        jnp.sum((g.astype(jnp.float32) * m) ** 2)
                        for g, m in zip(flat_grads, clip_masks)))
                    scale = clip.clip_norm / jnp.maximum(gn, clip.clip_norm)
                    flat_grads = [
                        jnp.where(m > 0, g * scale.astype(g.dtype), g)
                        for g, m in zip(flat_grads, clip_masks)]
                new_ps, new_sts = [], []
                for gi, (pbuf, g, st) in enumerate(
                        zip(flat_ps, flat_grads, flat_sts)):
                    if reg_vecs[gi] is not None:
                        g = g + reg_vecs[gi].astype(pbuf.dtype) * pbuf
                    ctx = ctx_vecs[gi]
                    p2, s2 = opt._update(pbuf, g, dict(st), lr, step_no,
                                         ctx)
                    new_ps.append(p2)
                    new_sts.append(s2)
                return loss, new_ps, new_sts, effects

            fused_jit = jax.jit(fused_step, donate_argnums=(0, 2))

            def pack(train_raws, states):
                flat_ps, flat_sts = [], []
                for dt, idxs, offs, n in gmeta:
                    flat_ps.append(jnp.concatenate(
                        [train_raws[i].reshape(-1) for i in idxs]))
                    st = {}
                    for k in state_keys:
                        st[k] = jnp.concatenate(
                            [states[i][k].reshape(-1) for i in idxs])
                    flat_sts.append(st)
                return flat_ps, flat_sts

            def unpack_back(flat_ps, flat_sts, fixed):
                for (dt, idxs, offs, n), buf, st in zip(gmeta, flat_ps,
                                                        flat_sts):
                    for (o, sz, shp), i in zip(offs, idxs):
                        p = trainable[i]
                        p._data = buf[o:o + sz].reshape(shp)
                        p._inplace_version += 1
                        opt._state[stable_uid(p)] = {
                            k: st[k][o:o + sz].reshape(shp)
                            for k in state_keys}
                for h, fj in zip(holders, eff_fixed_idx):
                    h._data = fixed[fj]
                    h._inplace_version += 1

            result = (pack, unpack_back, fused_jit, eff_fixed_idx)
            break
        self._fused_loop_key = self._train_sig
        self._fused_loop = result
        return result

    def train_batch(self, inputs, labels=None, update=True):
        """One fused train step (reference: model.py train_batch)."""
        meter = self._step_meter
        if meter is None and not _otrace._ENABLED[0]:
            return self._train_batch_impl(inputs, labels, update)
        t0 = _time.perf_counter()
        with _otrace.span("train/step"):
            out = self._train_batch_impl(inputs, labels, update)
        if meter is not None:
            # the impl's float(loss) fetch synchronizes, so this wall time
            # is real device+host step time, not async-dispatch time
            ts = self._train_step_fn
            meter.step(_time.perf_counter() - t0,
                       flops=ts.get("flops") if ts else None)
        return out

    def _train_batch_impl(self, inputs, labels=None, update=True):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        x_raws = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
                  for i in inputs]
        y_raws = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
                  for l in labels]
        sig = (tuple((tuple(r.shape), str(r.dtype))
                     for r in x_raws + y_raws), bool(self._metrics))
        ts = self._get_train_step(sig)
        opt = self._optimizer
        for p in ts["trainable"]:
            if stable_uid(p) not in opt._state:
                opt._state[stable_uid(p)] = opt._init_state(p)
        opt._accumulators_built = True
        opt_states = [opt._state[stable_uid(p)] for p in ts["trainable"]]
        train_raws = [p._data for p in ts["trainable"]]
        fixed_raws = [ts["state"][i]._data for i in ts["fixed_pos"]]
        key = _gen.next_key()
        if self._step_meter is not None and "flops" not in ts:
            # once per compiled signature: XLA cost analysis of the fused
            # step (paddle.flops convention — see observability.stepmeter)
            from ..observability import stepmeter as _sm
            lr0 = jnp.asarray(opt.get_lr(), jnp.float32)
            st0 = jnp.asarray(1.0, jnp.float32)
            with _otrace.span("observability/cost_analysis"):
                ts["flops"] = _sm.compiled_flops(
                    ts["raw_step"], train_raws, fixed_raws, opt_states,
                    x_raws, y_raws, key, lr0, st0)
            self._step_meter.set_flops_per_step(ts["flops"])
        if not update:
            # gradient accumulation (reference train_batch(update=False)):
            # accumulate into .grad, defer clip/regularize/step
            loss, preds, grads, effects = ts["grads_fn"](
                train_raws, fixed_raws, x_raws, y_raws, key)
            for p, g in zip(ts["trainable"], grads):
                p._grad = g if p._grad is None else p._grad + g
        elif (any(p._grad is not None for p in ts["trainable"])
                or opt._sentinel is not None):
            # finishing an accumulation window: add this batch's grads to
            # the carried sum and let the eager optimizer (clip/regularize
            # inside step()) apply the combined update — reference
            # semantics for train_batch after update=False calls.
            # A sentinel-guarded optimizer takes this route too: its health
            # probe needs the grads materialized and its skip/rollback
            # decision happens in the Optimizer.step hook, neither of which
            # exists inside the fully-fused update program
            loss, preds, grads, effects = ts["grads_fn"](
                train_raws, fixed_raws, x_raws, y_raws, key)
            for p, g in zip(ts["trainable"], grads):
                p._grad = g if p._grad is None else p._grad + g
            if opt._sentinel is not None:
                opt._sentinel.observe(loss=loss)
            opt.step()
            opt.clear_grad()
        else:
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_no = jnp.asarray(opt._global_step + 1, jnp.float32)
            loss, preds, new_p, new_s, effects = ts["fn"](
                train_raws, fixed_raws, opt_states, x_raws, y_raws, key,
                lr, step_no)
            for p, npr, ns in zip(ts["trainable"], new_p, new_s):
                p._data = npr
                p._inplace_version += 1
                opt._state[stable_uid(p)] = ns
            opt._global_step += 1
        for h, v in zip(ts["meta"].get("effect_holders", []), effects):
            h._data = v
            h._inplace_version += 1
        metrics = self._update_metrics(preds, labels)
        return float(loss), metrics

    def _update_metrics(self, preds, labels):
        out = []
        for m in self._metrics:
            pt = [Tensor(p) for p in preds]
            r = m.compute(*pt, *labels)
            r = m.update(r if not isinstance(r, tuple) else r[0])
            out.append(r)
        return out

    def _eval_cache_get(self, sig):
        ef = self._eval_fns.get(sig)
        if ef is not None:
            self._eval_fns.move_to_end(sig)
        return ef

    def _eval_cache_put(self, sig, ef):
        if len(self._eval_fns) >= self._eval_fns_max:
            self._eval_fns.popitem(last=False)
        self._eval_fns[sig] = ef
        return ef

    def _build_eval_step(self, with_loss):
        """Compile (state, x, y) -> (preds, loss) — eval/predict as ONE
        cached XLA program per signature instead of per-op dispatch
        (reference: hapi/model.py:250 StaticGraphAdapter compiles a
        separate eval Program; per-op eager here would pay the device
        round-trip per op, ~100ms each through the axon tunnel)."""
        params, buffers = self._state()
        state = params + buffers
        loss_fn = self._loss
        net = self.network

        def ev(state_raws, x_raws, y_raws, key):
            with trace_context(key):
                with swap_params(state, state_raws):
                    with _ag.no_grad():
                        xs = [Tensor(r) for r in x_raws]
                        ys = [Tensor(r) for r in y_raws]
                        preds = net.forward(*xs)
                        preds_t = preds if isinstance(preds, (list, tuple)) \
                            else [preds]
                        if with_loss:
                            loss = loss_fn(*preds_t, *ys)
                            loss_raw = (loss._data if isinstance(loss, Tensor)
                                        else jnp.asarray(loss))
                        else:
                            loss_raw = jnp.zeros(())
            # eval-mode traces have no buffer effects (BN uses running
            # stats); any stray effect is deliberately not applied
            return [p._data for p in preds_t], loss_raw

        return {"fn": jax.jit(ev), "state": state}

    def eval_batch(self, inputs, labels=None):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        x_raws = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
                  for i in inputs]
        y_raws = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
                  for l in labels]
        with_loss = self._loss is not None and bool(labels)
        sig = (tuple((tuple(r.shape), str(r.dtype))
                     for r in x_raws + y_raws), with_loss)
        ef = self._eval_cache_get(sig)
        if ef is None:
            self.network.eval()
            ef = self._eval_cache_put(sig, self._build_eval_step(with_loss))
        preds, loss_raw = ef["fn"]([s._data for s in ef["state"]],
                                   x_raws, y_raws, _gen.next_key())
        loss = float(loss_raw) if with_loss else None
        metrics = self._update_metrics(preds, labels)
        return loss, metrics

    def predict_batch(self, inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        x_raws = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
                  for i in inputs]
        sig = (tuple((tuple(r.shape), str(r.dtype)) for r in x_raws),
               "predict")
        ef = self._eval_cache_get(sig)
        if ef is None:
            self.network.eval()
            ef = self._eval_cache_put(
                sig, self._build_eval_step(with_loss=False))
        preds, _ = ef["fn"]([s._data for s in ef["state"]], x_raws, [],
                            _gen.next_key())
        out = [Tensor(p) for p in preds]
        return out[0] if len(out) == 1 else out

    # ------------------------------------------------------------------
    def _as_loader(self, data, batch_size, shuffle, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers)
        return data  # assume iterable of batches

    @staticmethod
    def _split_batch(batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return list(batch[:-1]), [batch[-1]]
            return [batch[0]], []
        return [batch], []

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        """reference: hapi/model.py:1519."""
        loader = self._as_loader(train_data, batch_size, shuffle, num_workers)
        eval_loader = self._as_loader(eval_data, batch_size, False, num_workers)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, log_freq=log_freq,
                                verbose=verbose, save_freq=save_freq,
                                save_dir=save_dir,
                                metrics=[m.name() for m in self._metrics])
        self.stop_training = False
        cbks.on_train_begin()
        it = 0
        try:
            for epoch in range(epochs):
                cbks.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                logs = {}
                for step, batch in enumerate(loader):
                    cbks.on_train_batch_begin(step)
                    xs, ys = self._split_batch(batch)
                    self._last_batch = (xs, ys)  # for sentinel quarantine dumps
                    loss, metrics = self.train_batch(xs, ys)
                    logs = {"loss": loss}
                    for m, r in zip(self._metrics, metrics):
                        logs[m.name() if isinstance(m.name(), str) else
                             m.name()[0]] = r
                    cbks.on_train_batch_end(step, logs)
                    it += 1
                    if num_iters is not None and it >= num_iters:
                        break
                cbks.on_epoch_end(epoch, logs)
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    self.evaluate(eval_loader, verbose=verbose,
                                  callbacks=cbks.callbacks, _inner=True)
                if self.stop_training or (num_iters is not None
                                          and it >= num_iters):
                    break
        except Exception as e:
            # post-mortem timeline for the guarded loop; dump only when the
            # flight recorder is armed (observability.enable / env)
            from ..observability import flight as _flight
            _flight.record_event("train_loop_exception",
                                 {"error": f"{type(e).__name__}: {e}",
                                  "iteration": it})
            _flight.dump_if_armed("train_loop_exception")
            raise
        cbks.on_train_end(logs)

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None, _inner=False):
        loader = self._as_loader(eval_data, batch_size, False, num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            xs, ys = self._split_batch(batch)
            loss, _ = self.eval_batch(xs, ys)
            if loss is not None:
                losses.append(loss)
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            name = m.name()
            logs[name if isinstance(name, str) else name[0]] = m.accumulate()
        if callbacks is not None and _inner:
            from .callbacks import CallbackList
            CallbackList(callbacks).on_eval_end(logs)
        elif verbose:
            print("Eval:", logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = self._as_loader(test_data, batch_size, False, num_workers)
        outputs = []
        for batch in loader:
            xs, _ = self._split_batch(batch)
            out = self.predict_batch(xs)
            outputs.append(out.numpy() if isinstance(out, Tensor)
                           else [o.numpy() for o in out])
        if stack_outputs and outputs and isinstance(outputs[0], np.ndarray):
            return [np.concatenate(outputs, 0)]
        return outputs

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        framework_io.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            framework_io.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = framework_io.load(path + ".pdparams")
        self.network.set_state_dict(state)
        # retire every compiled program referencing old param objects
        self._invalidate_compiled()
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(framework_io.load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtype)


def summary(net: Layer, input_size=None, dtypes=None):
    """reference: hapi/model_summary.py — layer table + param counts."""
    rows = []
    total = 0
    trainable = 0
    for name, layer in net.named_sublayers(include_self=True):
        n_params = 0
        for _, p in layer._parameters.items():
            if p is not None:
                n_params += p.size
        for _, b in layer._buffers.items():
            if b is not None:
                n_params += b.size
        if name == "":
            continue
        rows.append((name, type(layer).__name__, n_params))
    seen = set()
    for _, p in net.named_parameters():
        if id(p) in seen:
            continue
        seen.add(id(p))
        total += p.size
        if p.trainable:
            trainable += p.size
    for _, b in net.named_buffers():
        if id(b) not in seen:
            total += b.size
            seen.add(id(b))
    print("-" * 64)
    print(f"{'Layer':<36}{'Type':<18}{'Params':>10}")
    print("=" * 64)
    for name, kind, n in rows:
        print(f"{name:<36}{kind:<18}{n:>10}")
    print("=" * 64)
    print(f"Total params: {total}")
    print(f"Trainable params: {trainable}")
    print(f"Non-trainable params: {total - trainable}")
    print("-" * 64)
    return {"total_params": total, "trainable_params": trainable}


# -- trace-audit registration (tools/analyze/trace, PTA009/PTA010) -----------

def _audit_hapi_train_spec():
    """The fused hapi train step (fwd + grad + optimizer update, donated
    param/opt buffers) built by Model._build_train_step on a tiny Linear
    regression — the production step-compilation path, minimally sized."""
    import numpy as np
    from ..core import audit
    from ..core.tensor import stable_uid
    from .. import nn, optimizer as optim
    from .. import ops as _ops

    net = nn.Linear(5, 2)
    model = Model(net)

    def mse(pred, y):
        return _ops.mean((pred - y) ** 2)

    opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
    model.prepare(optimizer=opt, loss=mse)
    x_shape, y_shape = (4, 5), (4, 2)
    sig = ((((x_shape), "float32"), ((y_shape), "float32")), False)
    ts = model._get_train_step(sig)
    for p in ts["trainable"]:
        if stable_uid(p) not in opt._state:
            opt._state[stable_uid(p)] = opt._init_state(p)
    base_train = [np.asarray(p._data)  # noqa: PTA002 -- audit-factory setup: one-time host snapshot of the init params, not a step-path sync
                  for p in ts["trainable"]]
    base_fixed = [np.asarray(ts["state"][i]._data)  # noqa: PTA002 -- audit-factory setup: one-time host snapshot, not a step-path sync
                  for i in ts["fixed_pos"]]
    base_states = jax.tree_util.tree_map(
        np.asarray, [opt._state[stable_uid(p)] for p in ts["trainable"]])

    def make_args(variant):
        # fresh arrays per call: donate_argnums=(0, 2) consumes them
        rng = np.random.default_rng(5 + variant)
        train_raws = [jnp.asarray(b) for b in base_train]
        fixed_raws = [jnp.asarray(b) for b in base_fixed]
        opt_states = jax.tree_util.tree_map(jnp.asarray, base_states)
        x_raws = [jnp.asarray(rng.standard_normal(x_shape), jnp.float32)]
        y_raws = [jnp.asarray(rng.standard_normal(y_shape), jnp.float32)]
        key = jax.random.PRNGKey(variant)
        lr = jnp.asarray(0.1, jnp.float32)
        step_no = jnp.asarray(1.0, jnp.float32)
        return (train_raws, fixed_raws, opt_states, x_raws, y_raws, key,
                lr, step_no)

    return audit.AuditSpec(fn=ts["raw_step"], make_args=make_args,
                           jit_kwargs={"donate_argnums": (0, 2)})


def _register_audit_entrypoints():
    from ..core import audit
    audit.register_entrypoint("hapi_train_step", _audit_hapi_train_spec,
                              tags=("train",))


_register_audit_entrypoints()
