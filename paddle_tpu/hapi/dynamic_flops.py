"""paddle.flops (reference: hapi/dynamic_flops.py — forward-hook-based
per-layer FLOP heuristics).

TPU-first: the forward is traced once and XLA's own cost analysis counts
the compiled program's floating-point operations — exact for every op in
the graph, including ones the reference's per-layer-type table misses
(the reference counts only Conv/Linear/BN/pool/activations it knows).
``custom_ops`` is accepted for API parity but unnecessary: the compiler
already counts everything; a warning says so when it is passed.
``print_detail`` prints the per-layer parameter table (same rows as
``paddle.summary``) with the XLA totals underneath.
"""
from __future__ import annotations

import warnings

import numpy as np

__all__ = ["flops"]


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count forward FLOPs of ``net`` for one input of ``input_size``.

    Returns the compiler-measured total (int). MACs convention note: the
    reference counts one multiply-accumulate as 1 FLOP for Conv/Linear;
    XLA counts 2 (mul + add). For comparability with the reference's
    published numbers, this function divides the compiler count by 2 —
    documented rather than hidden.
    """
    import jax
    from ..jit.functionalize import build_pure

    if custom_ops:
        warnings.warn(
            "paddle.flops: custom_ops is unnecessary here — XLA's cost "
            "analysis counts every op in the compiled graph; the "
            "argument is ignored", UserWarning, stacklevel=2)

    was_training = getattr(net, "training", False)
    net.eval()
    state = [p for _, p in net.named_parameters()] + \
            [b for _, b in net.named_buffers()]
    pure, _meta = build_pure(net.forward, state)
    key = jax.random.PRNGKey(0)
    param_raws = [p._data for p in state]

    def fwd(x):
        return pure(param_raws, [x], key, None)

    x_aval = jax.ShapeDtypeStruct(tuple(input_size), np.float32)
    compiled = jax.jit(fwd).lower(x_aval).compile()
    costs = compiled.cost_analysis()
    if isinstance(costs, (list, tuple)):
        costs = costs[0]
    total = int(costs.get("flops", 0.0) / 2.0)     # MAC convention

    if print_detail:
        from .model import summary
        summary(net, input_size=tuple(input_size))
        print(f"Total Flops: {total}  (XLA-measured, MAC convention)")
    if was_training:
        net.train()        # reference restores the caller's mode
    return total
