"""hapi high-level API (reference: python/paddle/hapi/)."""
from .model import Model, summary
from . import callbacks
