"""paddle.sysconfig (reference: python/paddle/sysconfig.py —
get_include/get_lib for building native extensions against the
framework). Here the native surface is csrc/ (the C inference API +
shm ring), so the paths point there."""
import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory of paddle_tpu_capi.h for C/C++ embedders."""
    return os.path.join(os.path.dirname(_ROOT), "csrc")


def get_lib():
    """Directory where built .so artifacts land (build-on-first-use)."""
    return os.path.join(os.path.dirname(_ROOT), "csrc", "build")
