"""Top-level API-surface tail (reference: python/paddle/__init__.py
exports not covered by a dedicated module here): add_n, is_tensor,
create_parameter, inplace-variant aliases, printoptions, and the
other-backend probe stubs a v2.0 porter may call. Grouped in one module
so the main __init__ stays an import manifest."""
from __future__ import annotations

import numpy as np

__all__ = ["add_n", "is_tensor", "create_parameter", "set_printoptions",
           "scatter_", "tanh_", "is_compiled_with_xpu",
           "is_compiled_with_npu", "is_compiled_with_rocm",
           "CUDAPinnedPlace", "NPUPlace", "XPUPlace",
           "get_cudnn_version", "get_cuda_rng_state",
           "set_cuda_rng_state", "ComplexTensor"]


def add_n(inputs, name=None):
    """reference: sum_op.cc (paddle.add_n) — elementwise sum of a tensor
    list (or a single tensor)."""
    from .core.tensor import Tensor
    from .ops.creation import clone
    if isinstance(inputs, Tensor):
        return clone(inputs)       # reference returns a NEW tensor
    if not inputs:
        raise ValueError("add_n: empty input list")
    if len(inputs) == 1:
        return clone(inputs[0])    # no aliasing for 1-element lists
    out = inputs[0]
    for t in inputs[1:]:
        out = out + t
    return out


def is_tensor(x):
    """reference: paddle.is_tensor."""
    from .core.tensor import Tensor
    return isinstance(x, Tensor)


def create_parameter(shape, dtype, name=None, attr=None,
                     default_initializer=None, is_bias=False):
    """reference: fluid/layers/tensor.py create_parameter — a free
    Parameter outside any Layer."""
    from .core.tensor import Parameter
    from .nn import initializer as I
    init = default_initializer
    if init is None and attr is not None:
        init = getattr(attr, "initializer", None)
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    p = Parameter(init(tuple(shape), dtype))
    if name:
        p.name = name
    return p


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """reference: paddle.set_printoptions — tensor repr goes through
    numpy here, so this forwards to numpy's printoptions."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def scatter_(x, index, updates, overwrite=True, name=None):
    """Inplace-variant alias (reference: paddle.scatter_): same math as
    scatter, the result written back into ``x`` under the inplace-version
    guard."""
    from .ops.math import scatter
    out = scatter(x, index, updates, overwrite=overwrite)
    # tape-recorded inplace: adopt data AND grad node; no version bump
    # (core/tensor.py _swap_payload contract)
    x._swap_payload(out)
    return x


def tanh_(x, name=None):
    """Inplace-variant alias (reference: paddle.tanh_) — the single
    implementation lives in nn.functional."""
    from .nn.functional import tanh_ as _t
    return _t(x)


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_rocm():
    return False


def _absent_place(kind):
    class _Place:
        def __init__(self, *a, **k):
            raise RuntimeError(
                f"{kind} is not available in this TPU build "
                f"(is_compiled_with_cuda()/xpu()/npu() report the "
                f"supported backends); use CPUPlace()/TPUPlace()")
    _Place.__name__ = kind
    return _Place


CUDAPinnedPlace = _absent_place("CUDAPinnedPlace")
NPUPlace = _absent_place("NPUPlace")
XPUPlace = _absent_place("XPUPlace")


def get_cudnn_version():
    """reference: paddle.get_cudnn_version — None: no cuDNN in a TPU
    build (mirrors the reference's behaviour when not compiled with
    CUDA)."""
    return None


def get_cuda_rng_state():
    """reference: paddle.get_cuda_rng_state — empty: no CUDA generators
    exist; the framework RNG is paddle.seed/Generator (core/generator)."""
    return []


def set_cuda_rng_state(state):
    if state:
        raise RuntimeError(
            "set_cuda_rng_state: no CUDA generators in a TPU build; "
            "seed the framework RNG with paddle.seed instead")


class ComplexTensor:
    """reference: paddle.ComplexTensor (v2.0 transitional API — removed
    upstream shortly after). Complex data is first-class in the plain
    Tensor here (complex64/complex128 via jnp), so this name only
    redirects."""

    def __init__(self, *a, **k):
        raise RuntimeError(
            "ComplexTensor was a transitional v2.0 API; complex dtypes "
            "are supported directly: paddle.to_tensor(np.array(..., "
            "dtype=np.complex64)) — see paddle.real/paddle.imag/"
            "paddle.conj")
