"""paddle.version (reference: generated python/paddle/version.py —
full_version/major/minor/patch/rc + show()). Mirrors the reference
snapshot's 2.0-era version surface for porters that gate on it."""
full_version = "2.0.0"
major = "2"
minor = "0"
patch = "0"
rc = "0"
istaged = False
commit = "paddle-tpu"
with_mkl = "OFF"


def show():
    print(f"full_version: {full_version}")
    print(f"major: {major}")
    print(f"minor: {minor}")
    print(f"patch: {patch}")
    print(f"rc: {rc}")
    print(f"commit: {commit}")
