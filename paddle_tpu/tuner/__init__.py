"""paddle_tpu.tuner: empirical Pallas-kernel autotuner.

TVM-style per-shape schedule search (PAPERS.md) scaled to this repo's
kernel families: instead of hand-picked 128x128 blocks everywhere, the
flash-attention kernels (ops/pallas_attention.py, the ring-flash chunk
kernel in distributed/fleet/sequence_parallel.py) and the ops/custom.py
Pallas kernels resolve their block/grid configuration per
``(shape, dtype, platform)`` key through this package:

1. **in-process memo** — after the first resolution a key costs one dict
   lookup on the kernel-call path (zero measurable overhead),
2. **on-disk winner cache** — ``PADDLE_TPU_TUNE_CACHE`` (default
   ``~/.cache/paddle_tpu/tuning/``), versioned JSON written by
   ``tools/autotune.py`` (or by tune-on-miss), shared by every process
   that mounts it — replicas and restarts reuse each other's search,
3. **committed defaults** — ``default_winners.json`` ships winners for
   the bench-model shapes so CI and cold fleets never tune from scratch,
4. **heuristic fallback** — the historical hardcoded config, so an empty
   cache is never worse than the pre-tuner behavior.

Active search happens only in ``tools/autotune.py`` or when
``PADDLE_TPU_AUTOTUNE=1`` opts into tune-on-miss (a training step must
never block on a surprise search by default).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional, Tuple

from . import runner, space, store
from .space import (compress_block_candidates, flash_candidates,
                    nms_candidates, paged_attn_candidates)
from .store import CACHE_VERSION, WinnerStore, cache_dir, store_for

__all__ = [
    "CACHE_VERSION", "WinnerStore", "cache_dir", "store_for",
    "flash_key", "nms_key", "compress_key", "paged_key",
    "get_flash_blocks", "get_nms_config", "get_compress_block",
    "get_paged_attn_config",
    "record_winner", "autotune_flash", "autotune_compress",
    "autotune_paged_attn",
    "tune_on_miss_enabled",
    "flash_candidates", "nms_candidates", "compress_block_candidates",
    "paged_attn_candidates",
    "clear_memo",
]

_ENV_AUTOTUNE = "PADDLE_TPU_AUTOTUNE"

#: resolved configs, keyed by canonical key string — the zero-overhead
#: tier consulted at kernel-call time
_MEMO: Dict[str, Optional[Dict[str, Any]]] = {}
_MEMO_LOCK = threading.Lock()


def clear_memo() -> None:
    with _MEMO_LOCK:
        _MEMO.clear()
    store._reset_for_tests()


def tune_on_miss_enabled() -> bool:
    return os.environ.get(_ENV_AUTOTUNE, "").strip() in ("1", "true", "on")


def _platform() -> str:
    import jax
    try:
        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def _ceil16(n: int) -> int:
    return max(16, -(-int(n) // 16) * 16)


# -- canonical keys -----------------------------------------------------------

def flash_key(q_len: int, kv_len: int, head_dim: int, dtype: str,
              causal: bool, platform: Optional[str] = None,
              ring: bool = False, bwd: bool = False) -> str:
    """Key for the flash-attention family. Lengths are canonicalized to
    the 16-row sublane grid (4095 and 4096 share a winner); ``ring``
    marks the divisor-constrained ring-flash chunk variant; ``bwd``
    selects the backward-kernel family (the dQ/dKV recomputation programs
    have a different VMEM/compute balance than the forward, so they tune
    separately)."""
    p = platform or _platform()
    if bwd:
        fam = "ring_flash_bwd" if ring else "flash_bwd"
    else:
        fam = "ring_flash" if ring else "flash_fwd"
    try:                 # canonicalize: np.dtype / jnp scalar type / str
        import numpy as _np
        dtype = _np.dtype(dtype).name
    except TypeError:
        dtype = str(dtype)
    return (f"{fam}|{p}|{dtype}|d{int(head_dim)}|q{_ceil16(q_len)}"
            f"|k{_ceil16(kv_len)}|c{int(bool(causal))}")


def paged_key(num_heads: int, head_dim: int, page_size: int, dtype: str,
              platform: Optional[str] = None) -> str:
    """Key for the paged decode-attention family
    (``ops/paged_attention.py``). The query is always one token per
    sequence, so the shape family is (heads, head_dim, page_size) — the
    sequence count only scales the grid, not the per-step block."""
    p = platform or _platform()
    try:                 # canonicalize: np.dtype / jnp scalar type / str
        import numpy as _np
        dtype = _np.dtype(dtype).name
    except TypeError:
        dtype = str(dtype)
    return (f"paged_attn|{p}|{dtype}|h{int(num_heads)}|d{int(head_dim)}"  # noqa: PTA001 -- heads/head_dim/page_size are python shape ints at trace time
            f"|p{int(page_size)}")  # noqa: PTA001 -- see above


def nms_key(k: int, platform: Optional[str] = None) -> str:
    return f"nms|{platform or _platform()}|k{int(k)}"


def compress_key(nelems: int, wire_dtype: str = "int8",
                 platform: Optional[str] = None) -> str:
    """Key for the compressed-allreduce quantize-block family. Gradient
    sizes are bucketed to the next power of two (a 900k and a 1M gradient
    share a winner) with a 64-element floor."""
    n = max(64, int(nelems))  # noqa: PTA001 -- nelems is x.size, a host python int at trace time
    bucket = 1 << (n - 1).bit_length()
    return f"compress|{platform or _platform()}|{wire_dtype}|n{bucket}"


# -- lookup (the kernel-call path) -------------------------------------------

def _resolve(key: str) -> Optional[Dict[str, Any]]:
    with _MEMO_LOCK:
        if key in _MEMO:
            return _MEMO[key]
    cfg = store_for(key.split("|", 2)[1]).lookup(key)
    with _MEMO_LOCK:
        _MEMO[key] = cfg
    return cfg


def get_flash_blocks(q_len: int, kv_len: int, head_dim: int, dtype: str,
                     causal: bool, ring: bool = False, bwd: bool = False
                     ) -> Optional[Tuple[int, int]]:
    """The tuned (block_q, block_k) for a flash-attention shape, or None
    when no winner is known (caller applies its heuristic default)."""
    cfg = _resolve(flash_key(q_len, kv_len, head_dim, dtype, causal,
                             ring=ring, bwd=bwd))
    if not cfg:
        return None
    try:
        return int(cfg["block_q"]), int(cfg["block_k"])
    except (KeyError, TypeError, ValueError):
        return None


def get_spec_verify_blocks(k: int, kv_len: int, head_dim: int,
                           dtype: str = "float32"
                           ) -> Optional[Tuple[int, int]]:
    """Tuned (block_q, block_k) for a speculative *verify* step: k+1
    candidate queries attending causally over a full kv row. The shape is
    just a causal flash instance (q = k+1, canonicalised to the same
    16-multiple families `flash_key` uses), so verify reuses the flash
    winner memo instead of growing a new family."""
    return get_flash_blocks(k + 1, kv_len, head_dim, dtype, causal=True)


def get_paged_attn_config(num_heads: int, head_dim: int, page_size: int,
                          dtype: str) -> Optional[Dict[str, Any]]:
    """The tuned config (``{"block_h": ...}``) for a paged
    decode-attention shape, or None when no winner is known (the kernel
    applies its dividing heuristic)."""
    return _resolve(paged_key(num_heads, head_dim, page_size, dtype))


def get_nms_config(k: int) -> Optional[Dict[str, Any]]:
    return _resolve(nms_key(k))


def get_compress_block(nelems: int, wire_dtype: str = "int8"
                       ) -> Optional[int]:
    """The tuned quantize block for a gradient-size family, or None when
    no winner is known (collective.py applies its 256 default)."""
    cfg = _resolve(compress_key(nelems, wire_dtype))
    if not cfg:
        return None
    try:
        return int(cfg["block"])
    except (KeyError, TypeError, ValueError):
        return None


def record_winner(key: str, config: Dict[str, Any],
                  us: Optional[float] = None) -> None:
    """Write a winner to the disk cache and refresh the memo."""
    store_for(key.split("|", 2)[1]).record(key, config, us=us)
    with _MEMO_LOCK:
        _MEMO[key] = dict(config)


# -- active search ------------------------------------------------------------

def autotune_flash(batch_heads: int, q_len: int, kv_len: int,
                   head_dim: int, dtype: str = "float32",
                   causal: bool = False, ring: bool = False,
                   bwd: bool = False, trials: int = 5,
                   interpret: Optional[bool] = None,
                   record: bool = True) -> Dict[str, Any]:
    """Search (block_q, block_k) for one flash-attention shape by timing
    the real kernel, and (by default) persist the winner.

    Returns ``{"block_q", "block_k", "us", "results"}``. Runs the actual
    ``_fa_fwd_with_lse`` program (or, with ``bwd=True``, the
    ``_fa_bwd_with_lse`` recomputation program over residuals produced by
    an untimed forward) — candidate pruning is VMEM-based, the scoring is
    wall clock with median-of-``trials``.
    """
    import jax
    import jax.numpy as jnp
    from ..ops import pallas_attention as fa

    if interpret is None:
        interpret = _platform() != "tpu"
    jdt = jnp.dtype(dtype)
    q16, k16 = _ceil16(q_len), _ceil16(kv_len)
    cands = flash_candidates(q_len, kv_len, head_dim,
                             itemsize=jdt.itemsize, require_divides=ring)
    kq = jax.random.PRNGKey(0)
    qb = jax.random.normal(kq, (batch_heads, q16, head_dim), jdt)
    kb = jax.random.normal(kq, (batch_heads, k16, head_dim), jdt)
    vb = jax.random.normal(kq, (batch_heads, k16, head_dim), jdt)
    scale = 1.0 / float(head_dim) ** 0.5

    def _padded(bq, bk):
        if q16 % bq or k16 % bk:
            # pad to the candidate's grid exactly like flash_attention()
            qq = jnp.pad(qb, ((0, 0), (0, -(-q16 // bq) * bq - q16),
                              (0, 0)))
            kk = jnp.pad(kb, ((0, 0), (0, -(-k16 // bk) * bk - k16),
                              (0, 0)))
            vv = jnp.pad(vb, ((0, 0), (0, -(-k16 // bk) * bk - k16),
                              (0, 0)))
            return qq, kk, vv
        return qb, kb, vb

    def make_runner(cand):
        bq, bk = cand
        qq, kk, vv = _padded(bq, bk)
        if not bwd:
            fn = jax.jit(lambda a, b, c: fa._fa_fwd_with_lse(
                a, b, c, causal, scale, bq, bk, interpret, kv_len)[0])
            return lambda: fn(qq, kk, vv)
        # backward lane: residuals come from one untimed forward at the
        # same grid; only the dQ/dKV recomputation programs are timed
        out, lse = jax.jit(lambda a, b, c: fa._fa_fwd_with_lse(
            a, b, c, causal, scale, bq, bk, interpret, kv_len))(qq, kk, vv)
        do = jax.random.normal(kq, qq.shape, jdt)
        fn = jax.jit(lambda a, b, c, g, o, l: fa._fa_bwd_with_lse(
            a, b, c, g, o, l, causal, scale, bq, bk, interpret, kv_len))
        return lambda: fn(qq, kk, vv, do, out, lse)

    best, best_t, results = runner.search(cands, make_runner,
                                          trials=trials)
    if best is None:
        raise RuntimeError(
            f"autotune_flash: no candidate built for shape "
            f"(bh={batch_heads}, q={q_len}, kv={kv_len}, d={head_dim}, "
            f"{dtype})")
    cfg = {"block_q": int(best[0]), "block_k": int(best[1])}
    us = best_t * 1e6
    if record:
        record_winner(flash_key(q_len, kv_len, head_dim, dtype, causal,
                                ring=ring, bwd=bwd), cfg, us=us)
    return dict(cfg, us=us, results=results)


def autotune_paged_attn(num_seqs: int, num_heads: int, head_dim: int,
                        page_size: int, pages_per_seq: int = 8,
                        dtype: str = "float32", trials: int = 5,
                        interpret: Optional[bool] = None,
                        record: bool = True) -> Dict[str, Any]:
    """Search ``block_h`` for one paged decode-attention shape by timing
    the real kernel over a synthetic full arena (every sequence owns
    ``pages_per_seq`` disjoint pages, positions at the last row — the
    worst-case page walk), and (by default) persist the winner under
    :func:`paged_key`."""
    import jax
    import jax.numpy as jnp
    from ..ops.paged_attention import paged_attention

    if interpret is None:
        interpret = _platform() != "tpu"
    jdt = jnp.dtype(dtype)
    num_pages = num_seqs * pages_per_seq
    kq = jax.random.PRNGKey(0)
    q = jax.random.normal(kq, (num_seqs, num_heads, head_dim), jdt)
    k_arena = jax.random.normal(
        kq, (num_pages + 1, page_size, num_heads, head_dim), jdt)
    v_arena = jax.random.normal(
        jax.random.PRNGKey(1), k_arena.shape, jdt)
    bt = jnp.arange(num_pages, dtype=jnp.int32).reshape(
        num_seqs, pages_per_seq)
    positions = jnp.full((num_seqs,), pages_per_seq * page_size - 1,
                         jnp.int32)
    cands = paged_attn_candidates(num_heads, head_dim, page_size,
                                  itemsize=jdt.itemsize)

    def make_runner(cand):
        bh = int(cand["block_h"])
        fn = jax.jit(lambda qq, kk, vv, b, p: paged_attention(  # noqa: PTA008 -- per-candidate kernels differ (block_h baked in); tuner intentionally compiles each once
            qq, kk, vv, b, p, block_h=bh, interpret=interpret))
        return lambda: fn(q, k_arena, v_arena, bt, positions)

    best, best_t, results = runner.search(cands, make_runner,
                                          trials=trials)
    if best is None:
        raise RuntimeError(
            f"autotune_paged_attn: no candidate built for shape "
            f"(s={num_seqs}, h={num_heads}, d={head_dim}, "
            f"page={page_size}, {dtype})")
    cfg = {"block_h": int(best["block_h"])}
    us = best_t * 1e6
    if record:
        record_winner(paged_key(num_heads, head_dim, page_size, dtype),
                      cfg, us=us)
    return dict(cfg, us=us, results=results)


def autotune_compress(nelems: int, wire_dtype: str = "int8",
                      trials: int = 5, record: bool = True
                      ) -> Dict[str, Any]:
    """Search the quantize block size for one gradient-size family by
    timing the jitted quantize→dequantize roundtrip (the stage whose cost
    the block size controls; the wire bytes per candidate are analytic
    and nearly flat past 64). Persists the winner under
    :func:`compress_key` so ``distributed.collective`` picks it up."""
    import jax
    import jax.numpy as jnp
    from ..distributed.collective import (_block_dequantize_int8,
                                          _block_quantize_int8)

    n = max(64, int(nelems))
    x = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)
    cands = [c["block"] for c in compress_block_candidates(n)]

    def make_runner(blk):
        pad = -(-n // blk) * blk - n

        def roundtrip(v):
            blocks = jnp.pad(v, (0, pad)).reshape(-1, blk)
            if wire_dtype == "bf16":
                return blocks.astype(jnp.bfloat16).astype(
                    jnp.float32).reshape(-1)[:n]
            q, s = _block_quantize_int8(blocks)
            return _block_dequantize_int8(q, s).reshape(-1)[:n]
        fn = jax.jit(roundtrip)
        return lambda: fn(x)

    best, best_t, results = runner.search(cands, make_runner,
                                          trials=trials)
    if best is None:
        raise RuntimeError(
            f"autotune_compress: no candidate ran for nelems={nelems}")
    cfg = {"block": int(best)}
    us = best_t * 1e6
    if record:
        record_winner(compress_key(nelems, wire_dtype), cfg, us=us)
    return dict(cfg, us=us, results=results)
