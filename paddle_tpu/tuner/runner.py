"""Empirical trial runner: time candidates, keep the median, prune early.

The contract mirrors TVM's measure loop at micro scale: every candidate
is compiled once (excluded from timing), then timed ``trials`` times with
a blocking fetch after each run; the score is the median, which is robust
to the one-off stalls a shared chip shows. Early pruning: after the first
timed run, a candidate already slower than ``prune_factor`` x the best
median so far is abandoned — on a 30-candidate space this cuts wall time
roughly in half without changing the winner.
"""
from __future__ import annotations

import statistics
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


def time_once(run: Callable[[], Any]) -> float:
    """One timed execution; ``run`` must return a device value (or
    anything with ``block_until_ready``) so the wait is real."""
    t0 = time.perf_counter()
    out = run()
    blocker = getattr(out, "block_until_ready", None)
    if blocker is not None:
        blocker()  # noqa: PTA002 -- tuner trial barrier: timing requires completion
    return time.perf_counter() - t0


def measure(run: Callable[[], Any], trials: int = 5,
            best_so_far: Optional[float] = None,
            prune_factor: float = 2.0) -> Optional[float]:
    """Median-of-``trials`` seconds for ``run`` (after one untimed
    warmup that also absorbs the compile). Returns None when the
    candidate fails to build/run, or when early pruning fires."""
    try:
        run_out = run()
        blocker = getattr(run_out, "block_until_ready", None)
        if blocker is not None:
            blocker()  # noqa: PTA002 -- warmup barrier before timing
        first = time_once(run)
    except Exception:
        return None
    if best_so_far is not None and first > best_so_far * prune_factor:
        return None                       # early pruning
    times = [first]
    for _ in range(max(0, trials - 1)):
        times.append(time_once(run))
    return statistics.median(times)


def search(candidates: List[Any],
           make_runner: Callable[[Any], Callable[[], Any]],
           trials: int = 5, prune_factor: float = 2.0
           ) -> Tuple[Optional[Any], Optional[float], Dict[str, float]]:
    """Time every candidate; returns (winner, winner_seconds, results).

    ``make_runner(candidate)`` returns the zero-arg callable to time (it
    may raise for unbuildable candidates — that candidate just scores
    None). ``results`` maps repr(candidate) -> median seconds for the
    candidates that completed, for reports and tests.
    """
    best: Optional[Any] = None
    best_t: Optional[float] = None
    results: Dict[str, float] = {}
    for cand in candidates:
        try:
            run = make_runner(cand)
        except Exception:
            continue
        t = measure(run, trials=trials, best_so_far=best_t,
                    prune_factor=prune_factor)
        if t is None:
            continue
        results[repr(cand)] = t
        if best_t is None or t < best_t:
            best, best_t = cand, t
    return best, best_t, results
