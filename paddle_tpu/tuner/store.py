"""Versioned on-disk winner cache for the Pallas kernel autotuner.

Layout: one JSON file per platform under the tune-cache directory
(``PADDLE_TPU_TUNE_CACHE`` or ``~/.cache/paddle_tpu/tuning/``):

    winners-<platform>.json
    {"version": 1, "platform": "tpu",
     "entries": {"<key>": {"config": {...}, "us": 123.4}}}

Keys are the canonical strings built by :mod:`paddle_tpu.tuner` (kernel
family + platform + dtype + shape fields), so a winner tuned by
``tools/autotune.py`` on one replica is found by every process that
mounts the same cache dir — and survives restarts.

Integrity rules (tested): a corrupt/truncated file, a version-mismatched
file, or a malformed entry is ignored with a warning and treated as
missing — the caller retunes or falls back to defaults; a bad cache can
never crash a training step or silently apply a stale block config.

A committed defaults table (``default_winners.json`` next to this
module) seeds cold fleets and CI: disk entries win over defaults, and
``record()`` writes only to disk, never to the package file.
"""
from __future__ import annotations

import json
import os
import threading
import warnings
from typing import Any, Dict, Optional

#: bump when the key grammar or entry schema changes: old caches are
#: ignored (with a warning), never reinterpreted
CACHE_VERSION = 1

_ENV_DIR = "PADDLE_TPU_TUNE_CACHE"
_DEFAULTS_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "default_winners.json")


def cache_dir() -> str:
    d = os.environ.get(_ENV_DIR, "").strip()
    if d:
        return os.path.expanduser(d)
    return os.path.join(os.path.expanduser("~/.cache/paddle_tpu"), "tuning")


def _load_table(path: str, what: str) -> Dict[str, Dict[str, Any]]:
    """Load one winners table; any integrity problem -> warn + {}."""
    try:
        with open(path) as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    except (OSError, ValueError, UnicodeDecodeError) as e:
        warnings.warn(
            f"paddle_tpu.tuner: ignoring unreadable/corrupt {what} "
            f"({path}): {e}; affected shapes will be retuned or use "
            f"built-in defaults")
        return {}
    if not isinstance(data, dict) or data.get("version") != CACHE_VERSION:
        warnings.warn(
            f"paddle_tpu.tuner: ignoring {what} ({path}) with version "
            f"{data.get('version') if isinstance(data, dict) else '?'} "
            f"(expected {CACHE_VERSION}); affected shapes will be retuned")
        return {}
    entries = data.get("entries")
    if not isinstance(entries, dict):
        warnings.warn(f"paddle_tpu.tuner: {what} ({path}) has no valid "
                      f"'entries' table; ignoring it")
        return {}
    good: Dict[str, Dict[str, Any]] = {}
    bad = 0
    for k, v in entries.items():
        if isinstance(k, str) and isinstance(v, dict) \
                and isinstance(v.get("config"), dict):
            good[k] = v
        else:
            bad += 1
    if bad:
        warnings.warn(f"paddle_tpu.tuner: dropped {bad} malformed "
                      f"entr{'y' if bad == 1 else 'ies'} from {path}")
    return good


class WinnerStore:
    """Per-platform winner table: disk entries over committed defaults,
    loaded once, then every lookup is a dict.get."""

    def __init__(self, platform: str, directory: Optional[str] = None):
        self.platform = platform
        self.directory = directory or cache_dir()
        self.path = os.path.join(self.directory,
                                 f"winners-{platform}.json")
        self._lock = threading.Lock()
        self._entries: Optional[Dict[str, Dict[str, Any]]] = None
        self._defaults: Optional[Dict[str, Dict[str, Any]]] = None

    def _ensure_loaded(self) -> None:
        if self._entries is not None:
            return
        with self._lock:
            if self._entries is None:
                self._defaults = _load_table(_DEFAULTS_FILE,
                                             "default-winners table")
                self._entries = _load_table(self.path, "tuning cache")

    def lookup(self, key: str) -> Optional[Dict[str, Any]]:
        """The winning config dict for ``key``, or None. Disk entries
        shadow the committed defaults."""
        self._ensure_loaded()
        hit = self._entries.get(key)
        if hit is None:
            hit = self._defaults.get(key)
        return None if hit is None else dict(hit.get("config", {}))

    def entry(self, key: str) -> Optional[Dict[str, Any]]:
        """Full entry (config + timing metadata), disk tier only."""
        self._ensure_loaded()
        e = self._entries.get(key)
        return None if e is None else dict(e)

    def record(self, key: str, config: Dict[str, Any],
               us: Optional[float] = None) -> None:
        """Persist a winner: update memory, then atomically rewrite the
        platform file (tmp + rename). I/O failures warn, never raise —
        tuning results are an optimization, not state."""
        self._ensure_loaded()
        entry: Dict[str, Any] = {"config": dict(config)}
        if us is not None:
            entry["us"] = float(us)
        with self._lock:
            self._entries[key] = entry
            payload = {"version": CACHE_VERSION, "platform": self.platform,
                       "entries": self._entries}
            tmp = self.path + ".tmp"
            try:
                os.makedirs(self.directory, exist_ok=True)
                with open(tmp, "w") as f:
                    json.dump(payload, f, indent=1, sort_keys=True)
                os.replace(tmp, self.path)
            except OSError as e:
                warnings.warn(f"paddle_tpu.tuner: could not persist "
                              f"winner cache to {self.path}: {e}")
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    def keys(self):
        self._ensure_loaded()
        return sorted(set(self._entries) | set(self._defaults))


_STORES: Dict[str, WinnerStore] = {}
_STORES_LOCK = threading.Lock()


def store_for(platform: str) -> WinnerStore:
    with _STORES_LOCK:
        st = _STORES.get(platform)
        if st is None or st.directory != cache_dir():
            st = WinnerStore(platform)
            _STORES[platform] = st
        return st


def _reset_for_tests() -> None:
    with _STORES_LOCK:
        _STORES.clear()
