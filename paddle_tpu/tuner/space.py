"""Candidate spaces for the kernel autotuner, with VMEM-footprint pruning.

TVM's schedule-search insight applies at Pallas granularity: the right
block/grid shape is a function of (shape, dtype, platform), not a
constant. The spaces here are deliberately small — tens of candidates —
because each trial costs a Mosaic compile; VMEM pruning (the ~16 MiB/core
budget, pallas_guide.md) cuts the obviously-unbuildable ones before any
compile is attempted.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

#: per-core VMEM on current TPU generations (pallas_guide.md); trials
#: budget 80% of it so the compiler keeps headroom for spills/semaphores
VMEM_BYTES = 16 * 1024 * 1024
VMEM_BUDGET = int(VMEM_BYTES * 0.8)

#: sublane tile: block rows must stay multiples of 16 so both f32 (8) and
#: bf16 (16) layouts are legal (ops/pallas_attention.py convention)
SUBLANE = 16

#: candidate block edges for the flash-attention family
FLASH_BLOCKS = (16, 32, 64, 128, 256, 512)


def _ceil_to(n: int, m: int) -> int:
    return -(-n // m) * m


def flash_vmem_bytes(block_q: int, block_k: int, kv_len: int,
                     head_dim: int, itemsize: int = 4) -> int:
    """VMEM-resident bytes for one flash-attention program instance.

    The forward kernel's BlockSpecs bring the q block, the FULL padded
    K/V sequence, and the output block into VMEM; the score block,
    accumulator and row stats live in registers/VMEM scratch. f32
    accumulation dominates the scratch terms regardless of input dtype.
    """
    kv_pad = _ceil_to(kv_len, block_k)
    q_blk = block_q * head_dim * itemsize
    kv_res = 2 * kv_pad * head_dim * itemsize
    scores = block_q * block_k * 4            # f32 score block
    acc = block_q * head_dim * 4              # f32 accumulator
    out = block_q * head_dim * itemsize
    stats = 2 * block_q * 4                   # m / l rows
    return q_blk + kv_res + scores + acc + out + stats


def blockspec_vmem_bytes(block_shapes, itemsize: int = 4) -> int:
    """Generic VMEM-resident bytes for a pallas_call's BlockSpec set: the
    sum of every block's element count times ``itemsize``. The family
    models above (:func:`flash_vmem_bytes`, :func:`paged_attn_vmem_bytes`)
    know their kernels' scratch/accumulator terms; this is the
    family-agnostic floor the static analyzer (PTA013) uses for arbitrary
    pallas_call sites — if the declared blocks alone bust the budget, no
    scratch accounting can save the kernel."""
    total = 0
    for shape in block_shapes:
        n = 1
        for d in shape:
            n *= int(d)
        total += n * itemsize
    return total


def flash_candidates(q_len: int, kv_len: int, head_dim: int,
                     itemsize: int = 4,
                     require_divides: bool = False
                     ) -> List[Tuple[int, int]]:
    """(block_q, block_k) candidates for a flash-attention shape, VMEM
    pruned. ``require_divides`` restricts to blocks that divide the
    16-rounded lengths exactly — the ring-flash path calls the kernel
    core without a padding wrapper, so only exact divisors are legal
    there."""
    q16 = max(SUBLANE, _ceil_to(q_len, SUBLANE))
    k16 = max(SUBLANE, _ceil_to(kv_len, SUBLANE))
    out: List[Tuple[int, int]] = []
    for bq in FLASH_BLOCKS:
        if bq > q16:
            continue
        if require_divides and q16 % bq:
            continue
        for bk in FLASH_BLOCKS:
            if bk > k16:
                continue
            if require_divides and k16 % bk:
                continue
            if flash_vmem_bytes(bq, bk, kv_len, head_dim,
                                itemsize) > VMEM_BUDGET:
                continue
            out.append((bq, bk))
    if not out:
        # tiniest legal block always fits; the caller's padding logic
        # clamps further
        out.append((SUBLANE, SUBLANE))
    return out


#: head-block candidates for the paged decode-attention family
#: (ops/paged_attention.py): how many heads share one grid step's page
#: DMA and dot. Must divide num_heads (the grid is H // block_h).
PAGED_BLOCK_H = (1, 2, 4, 8, 16, 32)


def paged_attn_vmem_bytes(block_h: int, page_size: int, head_dim: int,
                          itemsize: int = 4) -> int:
    """VMEM-resident bytes for one paged-attention program instance: the
    q/out head block, one K and one V page block, the f32 accumulator and
    the (block_h, 128)-padded running max/sum scratch."""
    q_blk = block_h * head_dim * itemsize
    kv_blk = 2 * page_size * block_h * head_dim * itemsize
    scores = block_h * page_size * 4
    acc = block_h * head_dim * 4
    stats = 2 * block_h * 128 * 4
    out = block_h * head_dim * itemsize
    return q_blk + kv_blk + scores + acc + stats + out


def paged_attn_candidates(num_heads: int, head_dim: int, page_size: int,
                          itemsize: int = 4) -> List[Dict[str, int]]:
    """block_h candidates for a paged decode-attention shape: divisors of
    ``num_heads`` only (the grid needs exact head tiling), VMEM pruned —
    though at decode page sizes the footprint is tiny, so pruning only
    bites on pathological page_size * head_dim products."""
    out = [{"block_h": b} for b in PAGED_BLOCK_H
           if b <= num_heads and num_heads % b == 0
           and paged_attn_vmem_bytes(b, page_size, head_dim,
                                     itemsize) <= VMEM_BUDGET]
    return out or [{"block_h": 1}]


#: candidate block sizes for the compressed-allreduce quantize stage.
#: Smaller blocks track outliers better (tighter scales) but pay more
#: scale-sidecar bytes; larger blocks amortize the sidecar but let one
#: outlier flatten a whole block's resolution.
COMPRESS_BLOCKS = (64, 128, 256, 512, 1024)


def compress_block_candidates(nelems: int) -> List[Dict[str, int]]:
    """Block-size candidates for one gradient-size family: a block larger
    than the payload only pads, so prune those."""
    out = [{"block": b} for b in COMPRESS_BLOCKS if b <= max(64, nelems)]
    return out or [{"block": COMPRESS_BLOCKS[0]}]


def nms_candidates(k: int) -> List[Dict[str, int]]:
    """Unroll factors for the greedy-NMS fori_loop (ops/custom.py): the
    loop body is tiny, so unrolling amortizes loop overhead until the
    unrolled body overflows instruction budget. Only exact divisors of
    the candidate count keep the trip arithmetic trivial."""
    return [{"unroll": u} for u in (1, 2, 4, 8) if u <= max(1, k)]
