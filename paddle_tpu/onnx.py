"""paddle.onnx.export (reference: python/paddle/onnx/export.py via
paddle2onnx — there a Program→ONNX converter; here a jaxpr→ONNX one).

A REAL exporter, self-contained: no onnx python package exists in this
image, so the ONNX ``ModelProto`` is serialized with a minimal protobuf
wire-format writer (field numbers per the public onnx.proto, opset 13).
The model's pure forward is traced to a jaxpr (same functionalization as
``jit.save``); parameters become initializers, each supported primitive
maps to an ONNX node, and unsupported primitives raise listing the op —
partial coverage is explicit, never silently-wrong output.

Supported primitive subset (covers MLP/conv/softmax nets AND
transformers — LeNet, ResNet-18, and GPT round-trip within 1e-4 in
the tests): general dot_general (canonicalising transposes + flattened
batched MatMul), embedding-style gather -> Gather, elementwise
arithmetic/min/max/pow/square, neg/exp/log/sqrt/rsqrt/abs/tanh/
logistic/erf/erfc/sign/floor, comparisons + select_n, reductions
(sum/max/min/mean via sum+div), reshape/transpose/broadcast/concat/
slice/squeeze/pad, convert_element_type, conv_general_dilated (NCHW),
reduce_window max (MaxPool) and add (AveragePool, count_include_pad),
iota (materialised), stop_gradient / copy (Identity).

``tests/test_onnx_export.py`` replays the serialized file with an
in-repo numpy interpreter (its own minimal protobuf reader) and checks
the outputs equal the framework's — the strongest validation available
without onnxruntime in the image.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["export"]

# -- minimal protobuf writer --------------------------------------------------

def _varint(n: int) -> bytes:
    out = b""
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            out += bytes([b])
            return out


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _int_field(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value)


def _str_field(field: int, s: str) -> bytes:
    return _len_field(field, s.encode("utf-8"))


# ONNX TensorProto.DataType
_DTYPES = {"float32": 1, "uint8": 2, "int8": 3, "int16": 5, "int32": 6,
           "int64": 7, "bool": 9, "float16": 10, "float64": 11,
           "uint32": 12, "uint64": 13, "bfloat16": 16}


def _tensor_proto(name: str, arr: np.ndarray) -> bytes:
    dt = _DTYPES.get(str(arr.dtype))
    if dt is None:
        raise NotImplementedError(f"onnx export: dtype {arr.dtype}")
    out = b""
    for d in arr.shape:
        out += _int_field(1, int(d))                 # dims
    out += _int_field(2, dt)                         # data_type
    out += _str_field(8, name)                       # name
    out += _len_field(9, np.ascontiguousarray(arr).tobytes())  # raw_data
    return out


def _value_info(name: str, shape, dtype) -> bytes:
    dt = _DTYPES.get(str(np.dtype(dtype)))
    shp = b""
    for d in shape:
        shp += _len_field(1, _int_field(1, int(d)))  # dim { dim_value }
    ttype = _int_field(1, dt) + _len_field(2, shp)   # elem_type, shape
    typ = _len_field(1, ttype)                       # type { tensor_type }
    return _str_field(1, name) + _len_field(2, typ)


def _attr_int(name, v):
    return _len_field(5, _str_field(1, name) + _tag(3, 0) + _varint(int(v))
                      + _int_field(20, 2))           # type=INT


def _attr_ints(name, vs):
    body = _str_field(1, name)
    for v in vs:
        body += _tag(8, 0) + _varint(int(v) & ((1 << 64) - 1))
    body += _int_field(20, 7)                        # type=INTS
    return _len_field(5, body)


def _node(op_type: str, inputs, outputs, attrs: bytes = b"",
          name: str = "") -> bytes:
    out = b""
    for i in inputs:
        out += _str_field(1, i)
    for o in outputs:
        out += _str_field(2, o)
    if name:
        out += _str_field(3, name)
    out += _str_field(4, op_type)
    out += attrs
    return _len_field(1, out)  # GraphProto.node


# -- jaxpr -> ONNX graph ------------------------------------------------------

class _Graph:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.names: Dict[int, str] = {}
        self._n = 0
        self._const_cache: Dict[bytes, str] = {}

    def fresh(self, hint="t"):
        self._n += 1
        return f"{hint}_{self._n}"

    def name_of(self, var):
        from jax._src.core import Literal
        if isinstance(var, Literal):
            return self.add_const(np.asarray(var.val))
        key = id(var)
        if key not in self.names:
            self.names[key] = self.fresh("v")
        return self.names[key]

    def add_const(self, arr: np.ndarray, hint="const"):
        arr = np.asarray(arr)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        ck = arr.tobytes() + str(arr.dtype).encode() + str(arr.shape).encode()
        if ck in self._const_cache:
            return self._const_cache[ck]
        nm = self.fresh(hint)
        self.initializers.append(_tensor_proto(nm, arr))
        self._const_cache[ck] = nm
        return nm

    def emit(self, op, ins, n_out=1, attrs=b""):
        outs = [self.fresh(op.lower()) for _ in range(n_out)]
        self.nodes.append(_node(op, ins, outs, attrs))
        return outs[0] if n_out == 1 else outs


def _np_dtype_name(aval):
    return str(np.dtype(aval.dtype))


def _convert_eqn(g: _Graph, eqn):
    prim = eqn.primitive.name
    p = eqn.params
    ins = [g.name_of(v) for v in eqn.invars]
    avals_in = [v.aval for v in eqn.invars]
    aval_out = eqn.outvars[0].aval if eqn.outvars else None

    def out(name_or_names):
        if isinstance(name_or_names, str):
            g.names[id(eqn.outvars[0])] = name_or_names
        else:
            for v, nm in zip(eqn.outvars, name_or_names):
                g.names[id(v)] = nm

    simple = {"add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
              "max": "Max", "min": "Min", "pow": "Pow", "neg": "Neg",
              "exp": "Exp", "log": "Log", "sqrt": "Sqrt", "abs": "Abs",
              "tanh": "Tanh", "logistic": "Sigmoid", "erf": "Erf",
              "sign": "Sign", "floor": "Floor", "ceil": "Ceil"}
    if prim in simple:
        return out(g.emit(simple[prim], ins))
    if prim == "rem":
        # lax.rem is truncated (dividend-sign) remainder = ONNX fmod=1;
        # fmod=0 would flip signs and is spec-invalid for floats
        return out(g.emit("Mod", ins, attrs=_attr_int("fmod", 1)))
    if prim == "erfc":
        e = g.emit("Erf", ins)
        one = g.add_const(np.asarray(1.0, np.dtype(avals_in[0].dtype)))
        return out(g.emit("Sub", [one, e]))
    if prim == "square":
        two = g.add_const(np.asarray(2.0, np.dtype(avals_in[0].dtype)))
        return out(g.emit("Pow", [ins[0], two]))
    if prim == "rsqrt":
        s = g.emit("Sqrt", ins)
        return out(g.emit("Reciprocal", [s]))
    if prim == "integer_pow":
        e = g.add_const(np.asarray(float(p["y"]), np.float32))
        return out(g.emit("Pow", [ins[0], e]))
    if prim in ("stop_gradient", "copy"):
        return out(g.emit("Identity", ins))
    if prim == "convert_element_type":
        dt = _DTYPES.get(str(np.dtype(p["new_dtype"])))
        if dt is None:
            raise NotImplementedError(
                f"onnx export: cast to {p['new_dtype']}")
        return out(g.emit("Cast", ins, attrs=_attr_int("to", dt)))
    if prim in ("gt", "lt", "ge", "le", "eq", "ne"):
        opm = {"gt": "Greater", "lt": "Less", "ge": "GreaterOrEqual",
               "le": "LessOrEqual", "eq": "Equal", "ne": "Equal"}
        r = g.emit(opm[prim], ins)
        if prim == "ne":
            r = g.emit("Not", [r])
        return out(r)
    if prim == "select_n":
        if len(ins) != 3:
            raise NotImplementedError("onnx export: select_n with "
                                      f"{len(ins) - 1} cases")
        # select_n(pred, on_false, on_true); Where(cond, X, Y): X if cond
        return out(g.emit("Where", [ins[0], ins[2], ins[1]]))
    if prim in ("reduce_sum", "reduce_max", "reduce_min"):
        axes = list(p["axes"])
        opm = {"reduce_sum": "ReduceSum", "reduce_max": "ReduceMax",
               "reduce_min": "ReduceMin"}
        if prim == "reduce_sum":   # opset 13: axes as input
            ax = g.add_const(np.asarray(axes, np.int64), "shape")
            return out(g.emit("ReduceSum", [ins[0], ax],
                              attrs=_attr_int("keepdims", 0)))
        return out(g.emit(opm[prim], ins,
                          attrs=_attr_ints("axes", axes)
                          + _attr_int("keepdims", 0)))
    if prim == "reshape":
        shp = g.add_const(np.asarray(p["new_sizes"], np.int64), "shape")
        return out(g.emit("Reshape", [ins[0], shp]))
    if prim == "squeeze":
        shp = g.add_const(np.asarray(aval_out.shape, np.int64), "shape")
        return out(g.emit("Reshape", [ins[0], shp]))
    if prim == "transpose":
        return out(g.emit("Transpose", ins,
                          attrs=_attr_ints("perm", p["permutation"])))
    if prim == "broadcast_in_dim":
        # reshape to put source dims in place, then Expand
        inter = [1] * len(p["shape"])
        for src, dst in enumerate(p["broadcast_dimensions"]):
            inter[dst] = avals_in[0].shape[src]
        rs = g.add_const(np.asarray(inter, np.int64), "shape")
        r = g.emit("Reshape", [ins[0], rs])
        es = g.add_const(np.asarray(p["shape"], np.int64), "shape")
        return out(g.emit("Expand", [r, es]))
    if prim == "concatenate":
        return out(g.emit("Concat", ins,
                          attrs=_attr_int("axis", p["dimension"])))
    if prim == "slice":
        if p.get("strides") is None:
            strides = [1] * len(p["start_indices"])
        else:
            strides = list(p["strides"])
        st = g.add_const(np.asarray(p["start_indices"], np.int64), "shape")
        en = g.add_const(np.asarray(p["limit_indices"], np.int64), "shape")
        ax = g.add_const(
            np.arange(len(strides), dtype=np.int64), "shape")
        sp = g.add_const(np.asarray(strides, np.int64), "shape")
        return out(g.emit("Slice", [ins[0], st, en, ax, sp]))
    if prim == "pad":
        lo, hi, interior = zip(*p["padding_config"])
        if any(i for i in interior):
            raise NotImplementedError("onnx export: interior padding")
        pads = g.add_const(np.asarray(list(lo) + list(hi), np.int64),
                           "shape")
        return out(g.emit("Pad", [ins[0], pads, ins[1]]))
    if prim == "iota":
        shape = p["shape"]
        dim = p["dimension"]
        arr = np.arange(shape[dim], dtype=np.dtype(p["dtype"]))
        arr = np.broadcast_to(
            arr.reshape([-1 if i == dim else 1
                         for i in range(len(shape))]), shape).copy()
        return out(g.add_const(arr, "iota"))
    if prim == "gather":
        dn = p["dimension_numbers"]
        op_aval, idx_aval = avals_in
        ss = tuple(p["slice_sizes"])
        # embedding-style take along axis 0: whole rows selected by a
        # trailing size-1 index vector -> ONNX Gather(axis=0)
        ok = (tuple(dn.collapsed_slice_dims) == (0,)
              and tuple(dn.start_index_map) == (0,)
              and not dn.operand_batching_dims
              and not dn.start_indices_batching_dims
              and ss == (1,) + tuple(op_aval.shape[1:])
              and tuple(dn.offset_dims) == tuple(
                  range(idx_aval.ndim - 1,
                        idx_aval.ndim - 1 + op_aval.ndim - 1)))
        if not ok:
            raise NotImplementedError(
                f"onnx export: general gather {dn} (only axis-0 row "
                f"take / embedding lookup maps to ONNX Gather)")
        idx_shape = list(idx_aval.shape[:-1])
        rs = g.add_const(np.asarray(idx_shape, np.int64), "shape")
        idx = g.emit("Reshape", [ins[1], rs])   # drop index-vector dim
        # jax out-of-bounds semantics: ONNX Gather is undefined there,
        # so every non-PROMISE mode gets an explicit Clip on the indices.
        # For mode=clip that is exact; for jnp.take's default
        # FILL_OR_DROP an out-of-range id clamps to the edge row instead
        # of producing the fill value — a documented divergence confined
        # to inputs that were already out of the table's range.
        mode_name = getattr(p.get("mode"), "name", str(p.get("mode")))
        if "PROMISE" not in mode_name.upper():
            idt = np.dtype(idx_aval.dtype)   # Clip inputs must share T
            lo = g.add_const(np.asarray(0, idt))
            hi = g.add_const(np.asarray(op_aval.shape[0] - 1, idt))
            idx = g.emit("Clip", [idx, lo, hi])
        return out(g.emit("Gather", [ins[0], idx],
                          attrs=_attr_int("axis", 0)))
    if prim == "dot_general":
        ((lc, rc), (lb, rb)) = p["dimension_numbers"]
        la, ra = avals_in
        if len(lc) != 1 or len(rc) != 1 or len(lb) != len(rb):
            raise NotImplementedError(
                f"onnx export: dot_general dims {p['dimension_numbers']}")
        # canonicalise to batched MatMul: transpose both sides to
        # (batch..., free..., K) x (batch..., K, free...), flattening
        # multiple free dims through Reshape; dot_general's output order
        # (batch, lhs-free, rhs-free) matches MatMul's directly
        lfree = [d for d in range(la.ndim) if d not in lb and d != lc[0]]
        rfree = [d for d in range(ra.ndim) if d not in rb and d != rc[0]]
        lperm = list(lb) + lfree + [lc[0]]
        rperm = list(rb) + [rc[0]] + rfree
        lhs = ins[0]
        if lperm != list(range(la.ndim)):
            lhs = g.emit("Transpose", [lhs], attrs=_attr_ints("perm", lperm))
        rhs = ins[1]
        if rperm != list(range(ra.ndim)):
            rhs = g.emit("Transpose", [rhs], attrs=_attr_ints("perm", rperm))
        bshape = [la.shape[d] for d in lb]
        m = int(np.prod([la.shape[d] for d in lfree])) if lfree else 1
        n = int(np.prod([ra.shape[d] for d in rfree])) if rfree else 1
        k = la.shape[lc[0]]
        need_l_rs = len(lfree) != 1
        need_r_rs = len(rfree) != 1
        if need_l_rs:
            rs = g.add_const(np.asarray(bshape + [m, k], np.int64), "shape")
            lhs = g.emit("Reshape", [lhs, rs])
        if need_r_rs:
            rs = g.add_const(np.asarray(bshape + [k, n], np.int64), "shape")
            rhs = g.emit("Reshape", [rhs, rs])
        mm = g.emit("MatMul", [lhs, rhs])
        out_shape = (bshape + [la.shape[d] for d in lfree]
                     + [ra.shape[d] for d in rfree])
        if need_l_rs or need_r_rs:
            rs = g.add_const(np.asarray(out_shape, np.int64), "shape")
            mm = g.emit("Reshape", [mm, rs])
        return out(mm)
    if prim == "conv_general_dilated":
        dn = p["dimension_numbers"]
        if (dn.lhs_spec[0] != 0 or dn.lhs_spec[1] != 1
                or dn.rhs_spec[0] != 0 or dn.rhs_spec[1] != 1):
            raise NotImplementedError(
                f"onnx export: conv layout {dn}")
        lo = [a for a, _ in p["padding"]]
        hi = [b for _, b in p["padding"]]
        attrs = (_attr_ints("strides", p["window_strides"])
                 + _attr_ints("pads", lo + hi)
                 + _attr_ints("dilations", p["rhs_dilation"])
                 + _attr_int("group", p["feature_group_count"]))
        return out(g.emit("Conv", ins, attrs=attrs))
    if prim == "reduce_window_max":
        return out(_pool(g, ins, p, "MaxPool"))
    if prim == "reduce_window_sum":
        ap = _pool(g, ins, p, "AveragePool",
                   extra=_attr_int("count_include_pad", 1))
        n = int(np.prod([d for d in p["window_dimensions"] if d > 1]))
        sc = g.add_const(np.asarray(float(n), np.float32))
        return out(g.emit("Mul", [ap, sc]))
    if prim in ("pjit", "jit", "closed_call", "custom_jvp_call",
                "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
                "checkpoint"):
        inner = p.get("jaxpr") or p.get("call_jaxpr") or p.get("fun_jaxpr")
        if inner is None:
            raise NotImplementedError(f"onnx export: opaque call {prim}")
        closed = inner if hasattr(inner, "jaxpr") else None
        jx = closed.jaxpr if closed is not None else inner
        consts = closed.consts if closed is not None else []
        for cv, c in zip(jx.constvars, consts):
            g.names[id(cv)] = g.add_const(np.asarray(c), "const")
        for iv, nm in zip(jx.invars, ins):
            g.names[id(iv)] = nm
        for sub in jx.eqns:
            _convert_eqn(g, sub)
        return out([g.name_of(v) for v in jx.outvars])
    raise NotImplementedError(
        f"onnx export: primitive {prim!r} has no ONNX mapping (see "
        f"paddle_tpu/onnx.py for the supported subset; jit.save's "
        f"StableHLO artifact covers the full op set)")


def _pool(g, ins, p, op, extra=b""):
    wd = p["window_dimensions"]
    ws = p["window_strides"]
    pad = p["padding"]
    if wd[0] != 1 or wd[1] != 1:
        raise NotImplementedError("onnx export: pooling over batch/chan")
    lo = [a for a, _ in pad[2:]]
    hi = [b for _, b in pad[2:]]
    attrs = (_attr_ints("kernel_shape", wd[2:])
             + _attr_ints("strides", ws[2:])
             + _attr_ints("pads", lo + hi) + extra)
    return g.emit(op, [ins[0]], attrs=attrs)


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Trace ``layer`` and write a real ``.onnx`` file (opset 13). For a
    non-.onnx ``path`` this keeps the historical behaviour of writing the
    StableHLO artifact via jit.save."""
    from . import jit as _jit

    if opset_version != 13:
        raise ValueError(
            "onnx.export emits opset-13 constructs (ReduceSum axes input, "
            "GreaterOrEqual/LessOrEqual); declaring any other "
            f"opset_version ({opset_version}) would produce an invalid "
            "file")
    if not path.endswith(".onnx"):
        _jit.save(layer, path, input_spec=input_spec)
        return path

    import jax
    from .jit.functionalize import build_pure
    from .static import InputSpec
    from .nn.layer_base import Layer

    if input_spec is None:
        raise ValueError("onnx.export requires input_spec")
    specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
             for s in input_spec]
    if isinstance(layer, Layer):
        layer.eval()
        fwd = layer.forward
        fn = fwd._fn if hasattr(fwd, "_fn") else fwd
        state = [p for _, p in layer.named_parameters()] + \
                [b for _, b in layer.named_buffers()]
    else:
        fn, state = layer, []
    pure, meta = build_pure(fn, state)
    key = jax.random.PRNGKey(0)
    param_raws = [p._data for p in state]

    def infer(*input_raws):
        return pure(param_raws, list(input_raws), key, None)

    avals = [jax.ShapeDtypeStruct(
        tuple(d if d is not None else 1 for d in s.shape), s.dtype)
        for s in specs]
    closed = jax.make_jaxpr(infer)(*avals)
    n_out = meta["n_out"]

    g = _Graph()
    for cv, c in zip(closed.jaxpr.constvars, closed.consts):
        g.names[id(cv)] = g.add_const(np.asarray(c), "param")
    in_names = []
    for i, iv in enumerate(closed.jaxpr.invars):
        nm = f"input_{i}"
        g.names[id(iv)] = nm
        in_names.append(nm)
    for eqn in closed.jaxpr.eqns:
        _convert_eqn(g, eqn)
    out_names = [g.name_of(v) for v in closed.jaxpr.outvars[:n_out]]

    graph = b"".join(g.nodes)
    graph += _str_field(2, "paddle_tpu")
    graph += b"".join(_len_field(5, t) for t in g.initializers)
    for nm, av in zip(in_names, avals):
        graph += _len_field(11, _value_info(nm, av.shape, av.dtype))
    for nm, ov in zip(out_names, closed.jaxpr.outvars[:n_out]):
        graph += _len_field(12, _value_info(nm, ov.aval.shape,
                                            ov.aval.dtype))

    model = _int_field(1, 8)                         # ir_version
    model += _str_field(2, "paddle_tpu")             # producer_name
    model += _len_field(7, graph)                    # graph
    model += _len_field(8, _int_field(2, opset_version))  # opset_import
    with open(path, "wb") as f:
        f.write(model)
    return path
