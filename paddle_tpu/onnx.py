"""paddle.onnx API surface (reference: python/paddle/onnx/export.py —
paddle.onnx.export via paddle2onnx).

TPU design: the portable deployment artifact here is StableHLO
(`paddle_tpu.jit.save` → loadable by `paddle_tpu.inference.Predictor`, or
by any PJRT runtime). ONNX is a CUDA/CPU-deployment interchange format;
converting jaxpr→ONNX needs an external converter that is not part of
this image, so `export` writes the StableHLO artifact and tells the
caller exactly that, rather than failing obscurely.
"""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """API-parity export. Writes the StableHLO artifact at ``path`` (the
    same files jit.save produces) and raises if a true .onnx file was
    demanded, with the supported alternative spelled out."""
    from . import jit

    if path.endswith(".onnx"):
        raise NotImplementedError(
            "ONNX serialization requires an external jax->ONNX converter "
            "not bundled here; export the portable StableHLO artifact "
            "instead: paddle_tpu.jit.save(layer, prefix) -> "
            "paddle_tpu.inference.create_predictor runs it without any "
            "model code")
    jit.save(layer, path, input_spec=input_spec)
    return path
