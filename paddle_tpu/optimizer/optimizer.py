"""Optimizer base + the optimizer family, with a fused XLA update step.

Reference: the reference implements optimizers as *graph ops*
(paddle/fluid/operators/optimizers/{sgd,momentum,adam,adamax,adagrad,rmsprop,
lamb,...}_op.cc) appended by python/paddle/fluid/optimizer.py:58 `Optimizer`.
TPU design: each optimizer defines one pure `_update(param, grad, state, lr)`
rule; `step()` applies it across the whole parameter pytree inside a single
jitted computation with donated buffers (the analog of the reference's
fuse_optimizer_ops_pass + coalesce_tensor fusion, SURVEY Appendix B).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, stable_uid, Parameter
from ..core import dtypes as _dt
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        from ..ops.dispatch import in_dygraph_mode
        if parameters is None:
            if in_dygraph_mode():
                raise ValueError(
                    "parameters is required in dygraph mode "
                    "(pass model.parameters())")
            parameters = []  # static mode: params come from the Program
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._weight_decay = weight_decay
        self._state: Dict[int, dict] = {}
        self._global_step = 0
        self._jit_update = None
        self._jit_key = None
        self._accumulators_built = False
        self._sentinel = None  # set by paddle_tpu.sentinel.Sentinel.attach
        self.helper = None

    # -- lr -----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    # -- state --------------------------------------------------------------
    def _ensure_state(self):
        if self._accumulators_built:
            return
        for p in self._parameter_list:
            self._state[stable_uid(p)] = self._init_state(p)
        self._accumulators_built = True

    def _init_state(self, p: Parameter) -> dict:
        return {}

    def state_dict(self):
        """reference: python/paddle/optimizer/optimizer.py state_dict — moment
        accumulators + global step + LR scheduler state."""
        self._ensure_state()
        out = {}
        for i, p in enumerate(self._parameter_list):
            for k, v in self._state[stable_uid(p)].items():
                out[f"param_{i}.{k}"] = Tensor(v)
        out["global_step"] = self._global_step
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        self._ensure_state()
        for i, p in enumerate(self._parameter_list):
            for k in self._state[stable_uid(p)]:
                key = f"param_{i}.{k}"
                if key in state:
                    v = state[key]
                    self._state[stable_uid(p)][k] = (
                        v._data if isinstance(v, Tensor) else jnp.asarray(v))
        self._global_step = int(state.get("global_step", self._global_step))
        if "LR_Scheduler" in state and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])

    # -- update rule (override) ---------------------------------------------
    def _update(self, param, grad, state, lr, step, ctx=None):
        raise NotImplementedError

    def _regularized_grad(self, p, g):
        """Apply per-param L2 regularizer (reference: fluid/regularizer.py —
        appended as grad += coeff * param)."""
        reg = getattr(p, "regularizer", None)
        wd = self._weight_decay
        coeff = None
        if reg is not None and getattr(reg, "_coeff", None):
            coeff = reg._coeff
        elif isinstance(wd, (int, float)) and not getattr(self, "_decoupled_wd", False):
            coeff = float(wd)
        elif wd is not None and hasattr(wd, "_coeff") and not getattr(self, "_decoupled_wd", False):
            coeff = wd._coeff
        return coeff

    # -- step ---------------------------------------------------------------
    @property
    def _lr_dtype(self):
        return jnp.float32

    # True only when _update is purely elementwise on (p, g, state) so it
    # may run on a coalesced flat buffer (Model.train_loop); optimizers
    # with cross-element terms (LAMB/LARS trust ratios) must stay False.
    _elementwise_update = False

    def _param_update_ctx(self, params):
        """Per-param static context threaded into the fused update (hashable;
        part of the jit key). Subclasses override — e.g. AdamW returns
        (decay_coeff, lr_ratio) per param so apply_decay_param_fun-excluded
        params skip decoupled decay (reference: optimizer/adamw.py
        _append_decoupled_weight_decay's per-param skip)."""
        return [None] * len(params)

    def step(self):
        if self._sentinel is not None and \
                not self._sentinel.approve_step(self):
            return  # anomaly: the update is skipped, grads never applied
        self._ensure_state()
        params = [p for p in self._parameter_list if p._grad is not None
                  and p.trainable]
        if not params:
            return
        grads = [p._grad for p in params]
        if self._grad_clip is not None:
            grads = self._grad_clip._clip_raw(params, grads)
        states = [self._state[stable_uid(p)] for p in params]
        lr = jnp.asarray(self.get_lr(), self._lr_dtype)
        step_no = jnp.asarray(self._global_step + 1, jnp.float32)

        ctxs = self._param_update_ctx(params)
        key = (tuple((tuple(p.shape), str(p.dtype)) for p in params),
               tuple(ctxs))
        if self._jit_update is None or self._jit_key != key:
            reg_coeffs = [self._regularized_grad(p, None) for p in params]

            def fused(params_raw, grads_raw, states_raw, lr_, step_):
                new_p, new_s = [], []
                for pr, gr, st, rc, ctx in zip(params_raw, grads_raw,
                                               states_raw, reg_coeffs, ctxs):
                    if rc is not None:
                        gr = gr + rc * pr
                    p2, s2 = self._update(pr, gr.astype(pr.dtype), st, lr_,
                                          step_, ctx)
                    new_p.append(p2)
                    new_s.append(s2)
                return new_p, new_s
            self._jit_update = jax.jit(fused, donate_argnums=(0, 2))
            self._jit_key = key

        new_params, new_states = self._jit_update(
            [p._data for p in params], grads, states, lr, step_no)
        for p, np_, ns in zip(params, new_params, new_states):
            p._data = np_
            p._inplace_version += 1
            self._state[stable_uid(p)] = ns
        self._global_step += 1

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Dygraph: backward + step. Static mode: register this optimizer with
        the Program — the Executor compiles backward+update into the step
        (reference: fluid/optimizer.py minimize appends optimizer ops)."""
        from ..ops.dispatch import in_dygraph_mode
        if not in_dygraph_mode() and hasattr(loss, "_program"):
            from ..static.graph import Variable
            prog = loss._program
            prog._loss = loss
            prog._optimizer = self
            params_grads = []
            for i, p in enumerate(prog.all_parameters()):
                if p.stop_gradient:
                    continue
                gname = (p.name or f"param_{i}") + "@GRAD"
                gv = Variable(prog, p.shape, p.dtype, name=gname)
                prog.add_var(gv)
                prog._grad_map[gname] = p
                params_grads.append((p, gv))
            return None, params_grads
        loss.backward()
        self.step()
        return None, None

    def backward(self, loss, **kw):
        loss.backward()

    def apply_gradients(self, params_grads):
        for p, g in params_grads:
            p._grad = g._data if isinstance(g, Tensor) else g
        self.step()


class SGD(Optimizer):
    """reference: operators/optimizers/sgd_op.cc."""

    _elementwise_update = True

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update(self, p, g, s, lr, step, ctx=None):
        return p - lr.astype(p.dtype) * g, s


class Momentum(Optimizer):
    """reference: operators/optimizers/momentum_op.cc (use_nesterov attr)."""

    _elementwise_update = True

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": jnp.zeros(p._data.shape, p._data.dtype)}

    def _update(self, p, g, s, lr, step, ctx=None):
        lr = lr.astype(p.dtype)
        v = self._momentum * s["velocity"] + g
        if self._nesterov:
            p2 = p - lr * (g + self._momentum * v)
        else:
            p2 = p - lr * v
        return p2, {"velocity": v}


class Adam(Optimizer):
    """reference: operators/optimizers/adam_op.cc (bias-corrected)."""

    _elementwise_update = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._multi_precision = multi_precision

    def _init_state(self, p):
        dt = jnp.float32 if self._multi_precision else p._data.dtype
        st = {"moment1": jnp.zeros(p._data.shape, dt),
              "moment2": jnp.zeros(p._data.shape, dt)}
        if self._multi_precision and p._data.dtype != jnp.float32:
            st["master"] = p._data.astype(jnp.float32)
        return st

    def _update(self, p, g, s, lr, step, ctx=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        master = s.get("master")
        work = master if master is not None else p
        gf = g.astype(work.dtype)
        m = b1 * s["moment1"] + (1 - b1) * gf
        v = b2 * s["moment2"] + (1 - b2) * gf * gf
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        new_work = work - lr.astype(work.dtype) * mhat / (jnp.sqrt(vhat) + eps)
        ns = {"moment1": m, "moment2": v}
        if master is not None:
            ns["master"] = new_work
            return new_work.astype(p.dtype), ns
        return new_work, ns


class AdamW(Adam):
    """reference: python/paddle/optimizer/adamw.py (decoupled weight decay)."""

    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio
        if isinstance(weight_decay, (int, float)):
            self._coeff = float(weight_decay)
        elif isinstance(weight_decay, Tensor):
            self._coeff = float(weight_decay.numpy())  # noqa: PTA002 -- constructor-time, not in the step path
        else:
            raise TypeError(
                f"AdamW weight_decay must be a float or Tensor, got "
                f"{type(weight_decay).__name__}")

    def _param_update_ctx(self, params):
        ctxs = []
        for p in params:
            decay = True
            if self._apply_decay_param_fun is not None:
                decay = bool(self._apply_decay_param_fun(p.name or ""))
            ratio = 1.0
            if self._lr_ratio is not None:
                ratio = float(self._lr_ratio(p))
            ctxs.append((self._coeff if decay else 0.0, ratio))
        return ctxs

    def _update(self, p, g, s, lr, step, ctx=None):
        # decoupled decay first: p *= (1 - lr*ratio*coeff); excluded params
        # (biases/LayerNorm via apply_decay_param_fun) get coeff 0.
        coeff, ratio = ctx
        lr = lr * ratio
        master = s.get("master")
        work = master if master is not None else p
        decayed = work * (1.0 - lr.astype(work.dtype) * coeff)
        if master is not None:
            s = dict(s, master=decayed)
            out, ns = super()._update(p, g, s, lr, step)
            return out, ns
        return super()._update(decayed, g, s, lr, step)


class Adamax(Optimizer):
    """reference: operators/optimizers/adamax_op.cc."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, p):
        return {"moment": jnp.zeros(p._data.shape, p._data.dtype),
                "inf_norm": jnp.zeros(p._data.shape, p._data.dtype)}

    def _update(self, p, g, s, lr, step, ctx=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * s["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * s["inf_norm"], jnp.abs(g))
        p2 = p - (lr.astype(p.dtype) / (1 - b1 ** step)) * m / (u + eps)
        return p2, {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    """reference: operators/optimizers/adagrad_op.cc."""

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full(p._data.shape, self._init_acc, p._data.dtype)}

    def _update(self, p, g, s, lr, step, ctx=None):
        m = s["moment"] + g * g
        p2 = p - lr.astype(p.dtype) * g / (jnp.sqrt(m) + self._epsilon)
        return p2, {"moment": m}


class Adadelta(Optimizer):
    """reference: operators/optimizers/adadelta_op.cc."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = epsilon, rho

    def _init_state(self, p):
        return {"avg_sq_grad": jnp.zeros(p._data.shape, p._data.dtype),
                "avg_sq_update": jnp.zeros(p._data.shape, p._data.dtype)}

    def _update(self, p, g, s, lr, step, ctx=None):
        rho, eps = self._rho, self._epsilon
        ag = rho * s["avg_sq_grad"] + (1 - rho) * g * g
        upd = g * jnp.sqrt(s["avg_sq_update"] + eps) / jnp.sqrt(ag + eps)
        au = rho * s["avg_sq_update"] + (1 - rho) * upd * upd
        return p - lr.astype(p.dtype) * upd, {"avg_sq_grad": ag, "avg_sq_update": au}


class RMSProp(Optimizer):
    """reference: operators/optimizers/rmsprop_op.cc (centered variant)."""

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, p):
        st = {"mean_square": jnp.zeros(p._data.shape, p._data.dtype),
              "momentum": jnp.zeros(p._data.shape, p._data.dtype)}
        if self._centered:
            st["mean_grad"] = jnp.zeros(p._data.shape, p._data.dtype)
        return st

    def _update(self, p, g, s, lr, step, ctx=None):
        rho, eps = self._rho, self._epsilon
        ms = rho * s["mean_square"] + (1 - rho) * g * g
        if self._centered:
            mg = rho * s["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + eps)
        else:
            mg = None
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * s["momentum"] + lr.astype(p.dtype) * g / denom
        ns = {"mean_square": ms, "momentum": mom}
        if mg is not None:
            ns["mean_grad"] = mg
        return p - mom, ns


class Lamb(Optimizer):
    """reference: operators/optimizers/lamb_op.cc (layerwise adaptive)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        return {"moment1": jnp.zeros(p._data.shape, p._data.dtype),
                "moment2": jnp.zeros(p._data.shape, p._data.dtype)}

    def _update(self, p, g, s, lr, step, ctx=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * s["moment1"] + (1 - b1) * g
        v = b2 * s["moment2"] + (1 - b2) * g * g
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        r = mhat / (jnp.sqrt(vhat) + eps) + self._lamb_wd * p
        w_norm = jnp.sqrt(jnp.sum(p.astype(jnp.float32) ** 2))
        r_norm = jnp.sqrt(jnp.sum(r.astype(jnp.float32) ** 2))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - (lr * trust).astype(p.dtype) * r, {"moment1": m, "moment2": v}


class LarsMomentum(Optimizer):
    """reference: operators/optimizers/lars_momentum_op.cc."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay

    def _init_state(self, p):
        return {"velocity": jnp.zeros(p._data.shape, p._data.dtype)}

    def _update(self, p, g, s, lr, step, ctx=None):
        w_norm = jnp.sqrt(jnp.sum(p.astype(jnp.float32) ** 2))
        g_norm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            lr * self._lars_coeff * w_norm / (g_norm + self._lars_wd * w_norm),
            lr)
        v = self._momentum * s["velocity"] + local_lr.astype(p.dtype) * (
            g + self._lars_wd * p)
        return p - v, {"velocity": v}


class Ftrl(Optimizer):
    """reference: operators/optimizers/ftrl_op.cc."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _init_state(self, p):
        return {"squared": jnp.zeros(p._data.shape, p._data.dtype),
                "linear": jnp.zeros(p._data.shape, p._data.dtype)}

    def _update(self, p, g, s, lr, step, ctx=None):
        lp = self._lr_power
        new_sq = s["squared"] + g * g
        sigma = (jnp.power(new_sq, -lp) - jnp.power(s["squared"] + 1e-30, -lp)) / lr
        lin = s["linear"] + g - sigma * p
        quad = jnp.power(new_sq, -lp) / lr + 2 * self._l2
        pre = jnp.clip(lin, -self._l1, self._l1) - lin
        p2 = pre / quad
        return p2, {"squared": new_sq, "linear": lin}


@jax.jit
def _ema_step(emas, praws, d):
    return [d.astype(e.dtype) * e + (1.0 - d).astype(e.dtype) * p
            for e, p in zip(emas, praws)]


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference: fluid/optimizer.py:3694
    ExponentialMovingAverage — shadow vars updated as
    ema = decay * ema + (1 - decay) * param, with the optional
    ``thres_steps`` ramp decay' = min(decay, (1 + steps) / (10 + steps)),
    bias-corrected on apply; apply()/restore() swap params).

    Usage::

        ema = ExponentialMovingAverage(0.999,
                                       parameters=model.parameters())
        for batch in data:
            train_step(...)
            ema.update()
        with ema.apply():
            evaluate(...)

    (The reference registers every trainable param from the global static
    program at construction; dygraph has no global registry, so pass
    ``parameters=`` here or on the first ``update()``.)
    """

    def __init__(self, decay=0.999, thres_steps=None, name=None,
                 parameters=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._step = 0
        self._shadow = {}      # uid -> (param, ema_raw)
        self._backup = {}
        self._decay_pow = 1.0  # prod of decays for bias correction
        self._params = []
        if parameters is not None:
            self._register(parameters)

    def _register(self, params):
        for p in params:
            uid = stable_uid(p)
            if uid not in self._shadow and not p.stop_gradient:
                self._shadow[uid] = (p, jnp.zeros_like(p._data))
                self._params.append(p)

    def update(self, parameters=None):
        """One EMA step over the registered (or given) parameters."""
        if parameters is not None:
            self._register(parameters)
        elif not self._shadow:
            raise ValueError(
                "no parameters registered; pass parameters= to the "
                "constructor or to the first update()")
        self._step += 1
        d = self._decay
        if self._thres_steps is not None:
            d = min(d, (1.0 + self._step) / (10.0 + self._step))
        self._decay_pow *= d
        # one fused program for all shadows (not O(n_params) dispatches —
        # same reasoning as amp._fused_unscale)
        uids = list(self._shadow)
        emas = [self._shadow[u][1] for u in uids]
        praws = [self._shadow[u][0]._data for u in uids]
        new = _ema_step(emas, praws, jnp.asarray(d, jnp.float32))
        for u, e in zip(uids, new):
            self._shadow[u] = (self._shadow[u][0], e)

    def apply(self, parameters=None, need_restore=True):
        """Context manager: params hold their (bias-corrected) EMA values
        inside the block. ``parameters`` registers late additions; the
        swap always covers the full registered set."""
        import contextlib
        if parameters is not None:
            self._register(parameters)

        @contextlib.contextmanager
        def ctx():
            if self._step == 0:
                raise RuntimeError(
                    "ExponentialMovingAverage.apply() before any update(): "
                    "the shadow values are still zero-initialized")
            corr = 1.0 - self._decay_pow
            self._backup = {}
            for uid, (p, ema) in self._shadow.items():
                self._backup[uid] = p._data
                p._data = ema / corr if corr > 0 else ema
            try:
                yield self
            finally:
                if need_restore:
                    self.restore()
        return ctx()

    def restore(self, parameters=None):
        for uid, raw in self._backup.items():
            self._shadow[uid][0]._data = raw
        self._backup = {}
