"""Learning-rate schedulers.

Reference: python/paddle/optimizer/lr.py (LRScheduler base + NoamDecay,
PiecewiseDecay, NaturalExpDecay, InverseTimeDecay, PolynomialDecay,
LinearWarmup, ExponentialDecay, MultiStepDecay, StepDecay, LambdaDecay,
ReduceOnPlateau, CosineAnnealingDecay).
"""
from __future__ import annotations

import math


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def __call__(self):
        return self.last_lr

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()
        if self.verbose:
            print(f"Epoch {self.last_epoch}: lr = {self.last_lr}")

    def get_lr(self):
        raise NotImplementedError

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state.get("last_epoch", self.last_epoch)
        self.last_lr = state.get("last_lr", self.last_lr)

    set_dict = set_state_dict
    state_keys = state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1,
                 verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        return (self.base_lr * self.d_model ** -0.5
                * min(step ** -0.5, step * self.warmup_steps ** -1.5))


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = boundaries
        self.values = values
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        if self.cycle:
            div = math.ceil(step / self.decay_steps) if step > 0 else 1
            decay_steps = self.decay_steps * max(div, 1)
        else:
            decay_steps = self.decay_steps
            step = min(step, decay_steps)
        return ((self.base_lr - self.end_lr)
                * (1 - step / decay_steps) ** self.power + self.end_lr)


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr_sched = learning_rate  # float or LRScheduler
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        base = learning_rate.base_lr if isinstance(learning_rate, LRScheduler) \
            else float(learning_rate)
        super().__init__(base, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * (
                self.last_epoch / max(self.warmup_steps, 1)) + self.start_lr
        if isinstance(self.lr_sched, LRScheduler):
            self.lr_sched.last_epoch = self.last_epoch - self.warmup_steps
            return self.lr_sched.get_lr()
        return float(self.lr_sched)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** self.last_epoch


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones = milestones
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma ** n


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return (self.eta_min + (self.base_lr - self.eta_min)
                * (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2)


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3,
                 anneal_strategy="cos", three_phase=False, last_epoch=-1,
                 verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        super().__init__(self.initial_lr, last_epoch, verbose)

    def get_lr(self):
        up = int(self.phase_pct * self.total_steps)
        step = min(self.last_epoch, self.total_steps)
        if step <= up:
            pct = step / max(up, 1)
            return self.initial_lr + (self.max_lr - self.initial_lr) * (
                1 - math.cos(math.pi * pct)) / 2
        pct = (step - up) / max(self.total_steps - up, 1)
        return self.end_lr + (self.max_lr - self.end_lr) * (
            1 + math.cos(math.pi * pct)) / 2


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate, step_size_up,
                 step_size_down=None, mode="triangular", exp_gamma=1.0,
                 scale_fn=None, scale_mode="cycle", last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.up = step_size_up
        self.down = step_size_down or step_size_up
        self.mode = mode
        self.exp_gamma = exp_gamma
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        total = self.up + self.down
        cycle = self.last_epoch // total
        pos = self.last_epoch % total
        x = pos / self.up if pos < self.up else 1 - (pos - self.up) / self.down
        amp = self.max_lr - self.base_lr
        if self.mode == "triangular2":
            amp = amp / (2 ** cycle)
        elif self.mode == "exp_range":
            amp = amp * (self.exp_gamma ** self.last_epoch)
        return self.base_lr + amp * x


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self.base_lr = float(learning_rate)
        self.last_lr = self.base_lr
        self.last_epoch = 0
        self.verbose = verbose

    def get_lr(self):
        return self.last_lr

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        v = float(metrics.numpy()) if hasattr(metrics, "numpy") else float(metrics)  # noqa: PTA002 -- ReduceOnPlateau branches on the metric value; per-epoch, not per-step
        better = (self.best is None
                  or (self.mode == "min" and v < self.best - self.threshold)
                  or (self.mode == "max" and v > self.best + self.threshold))
        if better:
            self.best = v
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        elif self.num_bad > self.patience:
            self.last_lr = max(self.last_lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad = 0
