"""paddle.optimizer parity (reference: python/paddle/optimizer/__init__.py)."""
from .optimizer import (ExponentialMovingAverage,  # noqa: F401
                        Optimizer, SGD, Momentum, Adam, AdamW, Adamax,
                        Adagrad, Adadelta, RMSProp, Lamb, LarsMomentum, Ftrl)
from . import lr
