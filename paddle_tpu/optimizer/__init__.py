"""paddle.optimizer parity (reference: python/paddle/optimizer/__init__.py)."""
from .optimizer import (Optimizer, SGD, Momentum, Adam, AdamW, Adamax,
                        Adagrad, Adadelta, RMSProp, Lamb, LarsMomentum, Ftrl)
from . import lr
